"""Autoscaler chaos acceptance (slow tier): a 10x traffic spike against
one warm replica must grow the pool (each new replica warm-started with
ZERO compiles via the persistent compile cache), recover the burn signal
within a bounded window, keep the SequenceLedger audit clean (nothing
lost, nothing duplicated), and converge back to the floor after the
spike — even with a SIGKILL landing mid-scale-in."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu import monitor
from paddle_tpu._native import TCPStore
from paddle_tpu.core import flags as _flags
from paddle_tpu.inference.server import PredictorClient
from paddle_tpu.obs import telemetry as _telemetry
from paddle_tpu.serving import (Autoscaler, FleetRouter, ReplicaPool,
                                ScalePolicy)

FAST_FLEET = {"fleet_heartbeat_s": 0.1, "fleet_lease_ttl_s": 0.4,
              "fleet_health_interval_s": 0.1}


@pytest.fixture()
def fleet_flags():
    before = {k: _flags.flag(k) for k in FAST_FLEET}
    _flags.set_flags(FAST_FLEET)
    yield
    _flags.set_flags(before)


@pytest.fixture()
def monitored():
    monitor.reset()
    _flags.set_flags({"monitor": True})
    yield monitor
    _flags.set_flags({"monitor": False})
    monitor.reset()


def _store():
    return TCPStore("127.0.0.1", 0, is_master=True)


class SubprocessReplica:
    """The pool handle over one autoscaler_replica_runner.py child:
    `replica_id`/`poll` for the spawn loop, graceful `stop` (stdin line
    -> drain -> the runner's warm-start JSON report), `kill` for chaos."""

    def __init__(self, proc, replica_id, host, port):
        self.proc = proc
        self.replica_id = replica_id
        self.host = host
        self.port = int(port)
        self.report = None

    def poll(self):
        return self.proc.poll()

    def stop(self, drain=True):
        if self.proc.poll() is None:
            try:
                self.proc.stdin.write(b"done\n")
                self.proc.stdin.flush()
                self.proc.wait(timeout=60)
            except Exception:
                self.proc.kill()
                self.proc.wait(timeout=10)
        if self.report is None:
            try:
                out = self.proc.stdout.read() or b""
                for line in reversed(
                        out.decode(errors="replace").splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        self.report = json.loads(line)
                        break
            except Exception:
                pass

    def kill(self):
        self.proc.kill()
        self.proc.wait(timeout=10)


def _spawn_factory(store, fleet, tmp_path, cache_dir, all_handles):
    def spawn():
        tag = len(all_handles)
        port_file = str(tmp_path / f"replica-{tag}.port")
        env = dict(os.environ, JAX_PLATFORMS="cpu", FLAGS_monitor="1",
                   FLAGS_telemetry="1", FLAGS_telemetry_interval_s="0.05",
                   FLAGS_slo_latency_ms="100", FLAGS_slo_target="0.9",
                   FLAGS_slo_windows="5,60",
                   FLAGS_serving_queue_depth="2",
                   FLAGS_compile_cache_dir=cache_dir)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "autoscaler_replica_runner.py"),
             store.host, str(store.port), fleet, port_file],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        deadline = time.monotonic() + 90
        while not os.path.exists(port_file):
            assert proc.poll() is None, "replica died during startup"
            assert time.monotonic() < deadline, "replica never registered"
            time.sleep(0.05)
        rid, host, port = open(port_file).read().split()
        handle = SubprocessReplica(proc, int(rid), host, port)
        all_handles.append(handle)
        return handle
    return spawn


@pytest.mark.slow
class TestAutoscaleChaos:
    def test_spike_grows_pool_recovers_and_audits_clean(
            self, tmp_path, fleet_flags, monitored):
        store = _store()
        fleet = "autoscale"
        cache_dir = str(tmp_path / "compile-cache")
        collector = _telemetry.TelemetryCollector(store, fleet=fleet)
        collector.start()
        router = FleetRouter(store, fleet=fleet).start()
        all_handles = []
        pool = ReplicaPool(
            router, _spawn_factory(store, fleet, tmp_path, cache_dir,
                                   all_handles),
            spawn_timeout_s=90.0)
        # queue thresholds parked high: the drill's scale signal is the
        # burn — and a frozen post-traffic queue gauge must not wedge
        # the policy inside the hysteresis band
        policy = ScalePolicy(burn_high=1.0, burn_low=0.25,
                             queue_high=0.98, queue_low=0.9,
                             min_replicas=1, max_replicas=3,
                             cooldown_s=2.0, idle_after_s=4.0,
                             zero_after_s=3600.0, step=1)
        auto = Autoscaler(collector, pool, policy=policy,
                          interval_s=0.25, queue_capacity=2)
        stop_spike = threading.Event()
        stop_trickle = threading.Event()
        outcomes, lock = [], threading.Lock()

        def client(stop_ev):
            k = 0
            while not stop_ev.is_set():
                k += 1
                try:
                    st, _ = router.run(
                        [np.full((1, 4), float(k), np.float32)],
                        deadline_ms=8000)
                    with lock:
                        outcomes.append(st)
                except Exception as e:
                    with lock:
                        outcomes.append(repr(e))

        def worst_burn():
            return max([float(r.get("burn") or 0.0)
                        for r in collector.fleet_table()
                        if r.get("alive") and r.get("role") == "replica"]
                       or [0.0])

        threads = []
        try:
            # ---- steady state: the floor replica (cold: it PRIMES the
            # compile cache for every later spawn) ----------------------
            auto.start()
            deadline = time.monotonic() + 120
            while pool.actual() < 1 and time.monotonic() < deadline:
                time.sleep(0.1)
            assert pool.actual() == 1, "bootstrap to min_replicas failed"
            floor_rid = all_handles[0].replica_id

            # ---- the 10x spike ----------------------------------------
            spike_at = time.monotonic()
            threads = [threading.Thread(target=client,
                                        args=(stop_spike,))
                       for _ in range(16)]
            [t.start() for t in threads]
            deadline = time.monotonic() + 60
            while pool.actual() < 2 and time.monotonic() < deadline:
                time.sleep(0.1)
            t_first_new = time.monotonic() - spike_at
            assert pool.actual() >= 2, (
                f"spike never grew the pool: burn={worst_burn()}, "
                f"ledger={auto.ledger.last()}")
            assert t_first_new < 60.0
            # keep pressing: the pool must climb to max (the pressure is
            # sized so two replicas still burn budget)
            deadline = time.monotonic() + 45
            while pool.actual() < 3 and time.monotonic() < deadline:
                time.sleep(0.1)
            assert pool.actual() == 3, (
                f"pool stalled at {pool.actual()}: burn={worst_burn()}, "
                f"ledger={auto.ledger.last()}")
            # every replica serves a few requests DIRECTLY — the
            # warm-start acceptance below ("first request with zero
            # trace compiles") must not depend on a late spawn winning
            # router traffic before the spike subsides
            for h in all_handles:
                if h.poll() is not None:
                    continue
                c = PredictorClient(h.host, h.port, failover=False)
                try:
                    for _ in range(3):
                        st, _out = c.run(
                            [np.full((1, 4), 1.0, np.float32)],
                            deadline_ms=8000)
                finally:
                    c.close()

            # ---- spike subsides to a trickle: the burn signal must
            # recover below the scale-out threshold in a bounded window
            stop_spike.set()
            [t.join(timeout=30) for t in threads]
            trickle = [threading.Thread(target=client,
                                        args=(stop_trickle,))
                       for _ in range(2)]
            [t.start() for t in trickle]
            threads = trickle
            recovered_at = None
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                b = worst_burn()
                if recovered_at is None and b < policy.burn_high:
                    recovered_at = time.monotonic()
                if b < policy.burn_low:   # decayed enough that the
                    break                 # frozen gauge reads calm
                time.sleep(0.25)
            assert recovered_at is not None, (
                f"burn never recovered below {policy.burn_high}: "
                f"{worst_burn()}")
            # fully calm before the traffic stops: the burn gauge
            # freezes at its last published value, and a value stuck in
            # the hysteresis band would block every idle scale-in
            assert worst_burn() < policy.burn_low

            # ---- SIGKILL mid-scale-in: wait for the first idle drain
            # to be RECORDED, then a victim dies out from under the
            # control loop while it is still working the pool down -----
            stop_trickle.set()
            [t.join(timeout=30) for t in threads]
            threads = []
            deadline = time.monotonic() + 45
            while (auto.ledger.snapshot()["counts"].get("in", 0) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.2)
            assert auto.ledger.snapshot()["counts"].get("in", 0) >= 1, (
                f"no idle scale-in fired: {auto.ledger.last()}")
            spawned = [h for h in all_handles
                       if h.replica_id != floor_rid
                       and h.poll() is None]
            assert spawned, "no spike-spawned replica to chaos"
            victim = spawned[0]
            victim.kill()
            # converge to exactly the floor: the kill can momentarily
            # leave 0 alive (if the drain already took the floor
            # replica) — below_min respawns back up to 1
            deadline = time.monotonic() + 90
            while pool.actual() != 1 and time.monotonic() < deadline:
                time.sleep(0.2)
            assert pool.actual() == 1, (
                f"pool never converged to the floor: "
                f"{[h.replica_id for h in router.healthy_replicas()]}")
            # the SIGKILLed victim's record was reaped, not re-probed
            deadline = time.monotonic() + 15
            while (store.get(f"fleet:{fleet}:replica:"
                             f"{victim.replica_id}") != b""
                   and time.monotonic() < deadline):
                time.sleep(0.2)
            assert store.get(
                f"fleet:{fleet}:replica:{victim.replica_id}") == b""
            assert victim.replica_id not in router.replicas

            # ---- the soak's contract ----------------------------------
            n = len(outcomes)
            assert n > 100, f"burst too small to mean anything: {n}"
            errors = [o for o in outcomes if not isinstance(o, int)]
            assert len(errors) / n <= 0.01, (
                f"error rate {len(errors)}/{n}: {errors[:5]}")
            a = router.ledger.audit()
            assert a["lost"] == 0, a
            assert a["open"] == 0, a
            assert a["settled"] + a["rejected"] == a["issued"], a
            led = auto.ledger.snapshot()
            assert led["counts"].get("out", 0) >= 1
            assert led["counts"].get("in", 0) >= 1

            # ---- warm-start acceptance: graceful stops yield reports --
            auto.close(stop_pool=True)
            reports = {h.replica_id: h.report for h in all_handles
                       if h.report is not None}
            floor_report = reports.get(floor_rid)
            assert floor_report is not None
            # the floor replica was COLD: it paid the trace compiles and
            # stored the executables every later spawn loads
            assert floor_report["trace_compile"] > 0
            assert floor_report["warm_start"]["stores"] > 0
            warm = [r for rid, r in reports.items() if rid != floor_rid]
            assert warm, "no spike-spawned replica survived to report"
            for r in warm:
                # spawned into the primed cache: served real traffic
                # with ZERO trace compiles (the jit ledger counter)
                assert r["trace_compile"] == 0, r
                assert r["warm_start"]["hits"] > 0, r
                assert r["served"] > 0, r
        finally:
            stop_spike.set()
            stop_trickle.set()
            [t.join(timeout=30) for t in threads]
            auto.close(stop_pool=True)
            for h in all_handles:
                if h.poll() is None:
                    h.stop(drain=False)
            router.close()
            collector.stop()
