"""Resilience plane: deterministic fault injection (`paddle_tpu.faults`)
driving every hardened distributed seam through injected connection
resets, delays, stalls, worker kills, and torn checkpoint writes — each
recovery visible in `paddle_tpu.monitor` counters.

Every test here is auto-marked `chaos` (tests/conftest.py) and the
conftest leak guard asserts no injection spec survives any test.
"""
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import faults, monitor
from paddle_tpu.core import flags as _flags


@pytest.fixture(autouse=True)
def _monitor_on():
    """Recovery counters are the observable contract — assert through the
    monitor plane, reset around every test."""
    paddle.set_flags({"FLAGS_monitor": True})
    monitor.reset()
    yield
    paddle.set_flags({"FLAGS_monitor": False})
    monitor.reset()


class DictStore:
    """In-memory TCPStore stand-in (set/get/add contract incl. the
    native add-counter namespace): lets bus/elastic tests run without
    the C++ toolchain or extra processes."""

    def __init__(self):
        self._kv = {}
        self._counters = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._kv[k] = v.encode() if isinstance(v, str) else bytes(v)

    def get(self, k):
        with self._lock:
            if k not in self._kv:
                raise KeyError(k)
            return self._kv[k]

    def add(self, k, n):
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + n
            return self._counters[k]


# ---------------------------------------------------------------------------
# registry / spec grammar / determinism
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_flag_spec_round_trip(self):
        paddle.set_flags(
            {"FLAGS_fault_inject": "ps.rpc:conn_reset:p=0.2:seed=7"})
        try:
            assert faults.enabled()
            assert any("ps.rpc:conn_reset" in s for s in faults.active())
        finally:
            paddle.set_flags({"FLAGS_fault_inject": ""})
        assert not faults.enabled()
        assert faults.active() == []

    def test_bad_specs_rejected(self):
        with pytest.raises(faults.FaultSpecError):
            faults.register("justasite")
        with pytest.raises(faults.FaultSpecError):
            faults.register("s:not_a_kind")
        with pytest.raises(faults.FaultSpecError):
            faults.register("s:error:bogus=1")

    def test_kinds_raise_typed_errors(self):
        with faults.inject("a:conn_reset"):
            with pytest.raises(ConnectionResetError):
                faults.check("a")
        with faults.inject("b:timeout"):
            with pytest.raises(TimeoutError):
                faults.check("b")
        with faults.inject("c:error"):
            with pytest.raises(faults.InjectedFault):
                faults.check("c")

    def test_delay_kind_sleeps_not_raises(self):
        with faults.inject("d:delay:delay=0.05"):
            t0 = time.monotonic()
            faults.check("d")               # no raise
            assert time.monotonic() - t0 >= 0.04

    def test_times_and_after_budgets(self):
        with faults.inject("t:error:times=2:after=1"):
            faults.check("t")               # hit 1 skipped (after=1)
            for _ in range(2):              # hits 2..3 fire
                with pytest.raises(faults.InjectedFault):
                    faults.check("t")
            faults.check("t")               # budget exhausted: pass

    def test_seeded_probability_is_deterministic(self):
        def fire_pattern():
            pattern = []
            with faults.inject("p:error:p=0.5:seed=123"):
                for _ in range(32):
                    try:
                        faults.check("p")
                        pattern.append(0)
                    except faults.InjectedFault:
                        pattern.append(1)
            return pattern
        a, b = fire_pattern(), fire_pattern()
        assert a == b                       # same seed -> same sequence
        assert 0 < sum(a) < 32              # and it is actually p<1

    def test_prefix_site_matching(self):
        with faults.inject("ps.rpc:error:times=2"):
            with pytest.raises(faults.InjectedFault):
                faults.check("ps.rpc.send")
            with pytest.raises(faults.InjectedFault):
                faults.check("ps.rpc.recv")
        with faults.inject("ps:error"):     # dotted prefix only
            faults.check("psx.other")       # no fire: not a ps.* site

    def test_site_context_and_decorator(self):
        calls = []

        @faults.site("deco.site")
        def fn(x):
            calls.append(x)
            return x * 2

        assert fn(3) == 6                   # disabled: plain passthrough
        with faults.inject("deco.site:error:times=1"):
            with pytest.raises(faults.InjectedFault):
                fn(4)
            assert fn(5) == 10
        with faults.inject("cm.site:error"):
            with pytest.raises(faults.InjectedFault):
                with faults.site("cm.site"):
                    raise AssertionError("site body must not run")
        assert calls == [3, 5]

    def test_hit_counters_in_monitor_and_stats(self):
        with faults.inject("h.site:error:times=1"):
            with pytest.raises(faults.InjectedFault):
                faults.check("h.site")
            faults.check("h.site")
        st = faults.stats()["h.site"]
        assert st["hits"] == 2 and st["injected"] == 1
        counters = monitor.snapshot()["counters"]
        assert counters["faults.injected"] == 1
        assert counters["faults.injected.h.site"] == 1


# ---------------------------------------------------------------------------
# PS RPC plane: retry + reconnect + exactly-once pushes + deadlines
# ---------------------------------------------------------------------------

@pytest.fixture()
def ps_cluster():
    from paddle_tpu.distributed.ps import PsClient, PsServer
    servers = [PsServer() for _ in range(2)]
    for s in servers:
        s.add_sparse_table("emb", dim=4, lr=0.5)
        s.run()
    client = PsClient([f"{s.host}:{s.port}" for s in servers],
                      max_retries=4, backoff_ms=5.0, call_timeout=30.0)
    client.register_sparse_dim("emb", 4)
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


class TestPsResilience:
    def test_pull_survives_injected_send_resets(self, ps_cluster):
        servers, client = ps_cluster
        ids = np.array([0, 1, 2, 3], np.int64)
        base = client.pull_sparse("emb", ids)
        with faults.inject("ps.rpc.send:conn_reset:times=2"):
            got = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(got, base)
        counters = monitor.snapshot()["counters"]
        assert counters["ps.retries"] >= 1
        assert counters["faults.injected.ps.rpc.send"] == 2

    def test_pull_survives_recv_resets_with_reconnect(self, ps_cluster):
        servers, client = ps_cluster
        ids = np.array([2, 5], np.int64)
        base = client.pull_sparse("emb", ids)
        with faults.inject("ps.rpc.recv:conn_reset:times=1"):
            got = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(got, base)
        counters = monitor.snapshot()["counters"]
        assert counters["ps.reconnects"] >= 1

    def test_push_applied_exactly_once_through_lost_ack(self, ps_cluster):
        """The retried push re-sends the SAME per-client request seq;
        the server's at-most-once ledger must drop the duplicate. lr=0.5
        and a unit gradient give row = base - 0.5 iff applied once."""
        servers, client = ps_cluster
        base = client.pull_sparse("emb", [42]).copy()
        # the server applies the push, then the injected reset eats the
        # ACK: without sequencing the retry would double-apply
        with faults.inject("ps.rpc.recv:conn_reset:times=1"):
            client.push_sparse("emb", [42], np.ones((1, 4), np.float32))
        after = client.pull_sparse("emb", [42])
        np.testing.assert_allclose(after, base - 0.5, rtol=1e-6)
        counters = monitor.snapshot()["counters"]
        assert counters["ps.retries"] >= 1

    def test_push_seq_across_both_shards(self, ps_cluster):
        servers, client = ps_cluster
        ids = np.array([10, 11, 12, 13], np.int64)   # both servers
        base = client.pull_sparse("emb", ids).copy()
        with faults.inject("ps.rpc.recv:conn_reset:times=2"):
            client.push_sparse("emb", ids, np.ones((4, 4), np.float32))
        after = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(after, base - 0.5, rtol=1e-6)

    def test_server_side_injected_reset_recovered(self, ps_cluster):
        """ps.server fires in the handler: the connection drops server-
        side, the client reconnects and the pull still succeeds."""
        servers, client = ps_cluster
        ids = np.array([0, 1], np.int64)
        base = client.pull_sparse("emb", ids)
        with faults.inject("ps.server:conn_reset:times=1"):
            got = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(got, base)

    def test_retries_exhausted_surfaces_transport_error(self, ps_cluster):
        servers, client = ps_cluster
        with faults.inject("ps.rpc.send:conn_reset"):   # unlimited
            with pytest.raises(OSError):
                client.pull_sparse("emb", [1, 2])

    def test_app_errors_are_not_retried(self, ps_cluster):
        from paddle_tpu.distributed.ps.service import PsError
        servers, client = ps_cluster
        client.register_sparse_dim("nope", 4)
        monitor.reset()
        with pytest.raises(PsError):
            client.pull_sparse("nope", [1])
        assert monitor.snapshot()["counters"].get("ps.retries", 0) == 0

    def test_stalled_server_hits_call_deadline(self):
        """A listener that accepts and then goes silent (stalled, not
        closed) must produce a timeout within the per-call deadline, not
        a hang — recv_exact's deadline at work in the PS client."""
        from paddle_tpu.distributed.ps import PsClient
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)
        accepted = []

        def accept_loop():
            try:
                while True:
                    c, _ = lsock.accept()
                    accepted.append(c)   # keep open, never respond
            except OSError:
                pass

        th = threading.Thread(target=accept_loop, daemon=True)
        th.start()
        try:
            client = PsClient([f"127.0.0.1:{lsock.getsockname()[1]}"],
                              max_retries=1, backoff_ms=5.0,
                              call_timeout=0.4)
            client.register_sparse_dim("emb", 4)
            t0 = time.monotonic()
            with pytest.raises(OSError):     # TimeoutError is-an OSError
                client.pull_sparse("emb", [1])
            assert time.monotonic() - t0 < 5.0
            client.close()
        finally:
            lsock.close()
            for c in accepted:
                c.close()


class TestRecvExactDeadline:
    def test_deadline_raises_timeout_on_stalled_peer(self):
        from paddle_tpu.utils.net import recv_exact
        a, b = socket.socketpair()
        try:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                recv_exact(a, 4, deadline=time.monotonic() + 0.2)
            assert 0.1 < time.monotonic() - t0 < 2.0
        finally:
            a.close()
            b.close()

    def test_deadline_untouched_when_data_arrives(self):
        from paddle_tpu.utils.net import recv_exact
        a, b = socket.socketpair()
        try:
            b.sendall(b"abcd")
            assert recv_exact(a, 4, deadline=time.monotonic() + 5) == b"abcd"
            assert a.gettimeout() is None    # socket timeout restored
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# fleet message bus: reconnect + PeerGoneError + stuck-interceptor guard
# ---------------------------------------------------------------------------

class TestBusResilience:
    def _bus_pair(self):
        # each bus blocks until the OTHER rank's endpoint appears in the
        # store, so the pair must rendezvous concurrently
        from paddle_tpu.distributed.fleet_executor import DistMessageBus
        store = DictStore()
        owner = {0: 0, 1: 1}
        buses = {}

        def make(rank):
            buses[rank] = DistMessageBus(store, rank, 2, owner)

        threads = [threading.Thread(target=make, args=(r,))
                   for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        return buses[0], buses[1]

    def test_injected_reset_reconnects_and_delivers(self):
        from paddle_tpu.distributed.fleet_executor import Message
        bus0, bus1 = self._bus_pair()
        try:
            inbox = bus1.register(1)
            bus0.send(Message(0, 1, "data", payload="warm", micro=0))
            assert inbox.get(timeout=10).payload == "warm"
            with faults.inject("bus.send:conn_reset:times=1"):
                bus0.send(Message(0, 1, "data", payload="after-reset",
                                  micro=1))
            assert inbox.get(timeout=10).payload == "after-reset"
            counters = monitor.snapshot()["counters"]
            assert counters["bus.reconnects"] >= 1
        finally:
            bus0.close()
            bus1.close()

    def test_dead_peer_raises_peer_gone_promptly(self):
        from paddle_tpu.distributed.fleet_executor import (
            DistFleetExecutor, PeerGoneError)
        bus0, bus1 = self._bus_pair()
        bus1.close()                      # rank 1 is gone
        bus0._send_retries, bus0._send_backoff = 2, 0.01
        try:
            fx = DistFleetExecutor(my_stages={0: lambda x: x + 1},
                                   n_stages=2, stage_owner={0: 0, 1: 1},
                                   bus=bus0)
            t0 = time.monotonic()
            with pytest.raises(PeerGoneError) as ei:
                fx.run(microbatches=[np.zeros(2)], timeout=120.0)
            # prompt: seconds, nowhere near the 120s run timeout
            assert time.monotonic() - t0 < 30.0
            assert ei.value.rank == 1
        finally:
            bus0.close()

    def test_stuck_interceptor_join_raises_typed_error(self):
        from paddle_tpu.distributed.fleet_executor import (
            Interceptor, InterceptorStuckError, MessageBus, Message)
        bus = MessageBus()
        release = threading.Event()

        class Wedged(Interceptor):
            def handle(self, msg):
                release.wait()            # deadlocked handler

        actor = Wedged(7, bus)
        actor.start()
        bus.send(Message(-1, 7, "data"))
        time.sleep(0.1)                   # let it enter the wedge
        with pytest.raises(InterceptorStuckError, match="interceptor 7"):
            actor.join(timeout=0.3)
        release.set()                     # unwedge: thread drains + stops
        actor.join(timeout=10)


# ---------------------------------------------------------------------------
# DataLoader: dead worker detection + mid-epoch respawn
# ---------------------------------------------------------------------------

class SlowDs:
    def __init__(self, n=48, d=4, delay=0.01):
        self.x = np.arange(n * d, dtype=np.float32).reshape(n, d)
        self.delay = delay

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        time.sleep(self.delay)
        return self.x[i], np.int32(i)


class TestDataLoaderRespawn:
    def test_epoch_completes_through_worker_kill(self):
        from paddle_tpu.io import DataLoader
        dl = DataLoader(SlowDs(48), batch_size=4, num_workers=2,
                        shuffle=False, timeout=120)
        it = iter(dl)
        first = next(it)
        os.kill(it._workers[0].pid, signal.SIGKILL)   # hard worker death
        seen = list(np.asarray(first[1]._value))
        for xb, ib in it:
            seen.extend(np.asarray(ib._value).tolist())
        assert sorted(seen) == list(range(48))        # nothing lost
        assert seen == sorted(seen)                   # order preserved
        counters = monitor.snapshot()["counters"]
        assert counters["dataloader.worker_restarts"] >= 1

    def test_injected_worker_fault_respawns_and_completes(self):
        from paddle_tpu.io import DataLoader
        # fork-inherited spec: each initial worker dies on its first
        # batch; respawned workers clear the site and finish the epoch
        with faults.inject("dataloader.worker:error:times=1"):
            dl = DataLoader(SlowDs(32, delay=0.0), batch_size=4,
                            num_workers=2, shuffle=False, timeout=120)
            got = [np.asarray(ib._value).tolist() for _, ib in dl]
        flat = [i for b in got for i in b]
        assert sorted(flat) == list(range(32)) and flat == sorted(flat)
        counters = monitor.snapshot()["counters"]
        assert counters["dataloader.worker_restarts"] >= 1

    def test_restart_budget_exhaustion_is_a_hard_error(self):
        from paddle_tpu.io import DataLoader
        old = _flags.flag("dataloader_max_worker_restarts")
        paddle.set_flags({"FLAGS_dataloader_max_worker_restarts": 0})
        try:
            dl = DataLoader(SlowDs(48), batch_size=4, num_workers=2,
                            shuffle=False, timeout=60)
            it = iter(dl)
            next(it)
            os.kill(it._workers[0].pid, signal.SIGKILL)
            with pytest.raises(RuntimeError, match="respawn"):
                for _ in it:
                    pass
        finally:
            paddle.set_flags(
                {"FLAGS_dataloader_max_worker_restarts": old})


# ---------------------------------------------------------------------------
# checkpoint: crash-atomic commit + checksum fallback
# ---------------------------------------------------------------------------

class TestCheckpointAtomicity:
    def _save(self, tmp_path, scale):
        from paddle_tpu.framework.sharded_io import save_sharded
        save_sharded({"w": np.arange(16, dtype=np.float32) * scale,
                      "b": np.full(4, scale, np.float32)},
                     str(tmp_path))

    def test_crash_before_commit_keeps_previous_snapshot(self, tmp_path):
        from paddle_tpu.framework.sharded_io import load_sharded
        self._save(tmp_path, 1.0)
        with faults.inject("ckpt.commit:error:times=1"):
            with pytest.raises(faults.InjectedFault):
                self._save(tmp_path, 2.0)   # dies between data and commit
        got = load_sharded(str(tmp_path))
        np.testing.assert_allclose(got["w"], np.arange(16, dtype=np.float32))
        # no fallback needed: the manifest never moved off generation 1
        assert monitor.snapshot()["counters"].get("ckpt.fallbacks", 0) == 0

    def test_torn_write_detected_and_falls_back(self, tmp_path):
        from paddle_tpu.framework.sharded_io import load_sharded
        self._save(tmp_path, 1.0)
        with faults.inject("ckpt.write:torn:times=1"):
            self._save(tmp_path, 2.0)       # commits a torn shard file
        with pytest.warns(UserWarning, match="falling back"):
            got = load_sharded(str(tmp_path))
        np.testing.assert_allclose(got["w"],
                                   np.arange(16, dtype=np.float32))
        assert monitor.snapshot()["counters"]["ckpt.fallbacks"] >= 1

    def test_all_generations_corrupt_raises_typed_error(self, tmp_path):
        from paddle_tpu.framework.sharded_io import (
            CheckpointCorruptError, load_sharded)
        self._save(tmp_path, 1.0)
        import glob
        for npz in glob.glob(str(tmp_path / "shards-p*.npz")):
            with open(npz, "r+b") as f:
                f.truncate(max(1, os.path.getsize(npz) // 3))
        with pytest.raises(CheckpointCorruptError):
            load_sharded(str(tmp_path))

    def test_good_save_load_roundtrip_with_checksums(self, tmp_path):
        """Checksummed format round-trips cleanly and a second save GCs
        generations beyond the fallback window."""
        from paddle_tpu.framework.sharded_io import load_sharded
        import glob
        for scale in (1.0, 2.0, 3.0):
            self._save(tmp_path, scale)
        got = load_sharded(str(tmp_path))
        np.testing.assert_allclose(
            got["w"], np.arange(16, dtype=np.float32) * 3.0)
        kept = glob.glob(str(tmp_path / "shards-p*-v*.npz"))
        assert len(kept) == 2               # current + one fallback


# ---------------------------------------------------------------------------
# elastic: garbled leases + heartbeat fault tolerance
# ---------------------------------------------------------------------------

class TestElasticHardening:
    def test_alive_ranks_tolerates_garbled_lease(self):
        from paddle_tpu.parallel.elastic import ElasticManager
        store = DictStore()
        store.set("lease:0", b"\xff\xfenot-a-float")   # truncated/garbled
        store.set("lease:1", repr(time.time()))
        watcher = ElasticManager(store, rank=-1, world_size=2,
                                 lease_ttl=5.0)
        assert watcher.alive_ranks() == [1]            # no ValueError crash
        assert watcher.dead_ranks() == [0]

    def test_heartbeat_survives_transient_faults(self):
        from paddle_tpu.parallel.elastic import ElasticManager
        store = DictStore()
        node = ElasticManager(store, rank=0, world_size=1, lease_ttl=2.0,
                              heartbeat_interval=0.05)
        watcher = ElasticManager(store, rank=-1, world_size=1,
                                 lease_ttl=2.0)
        node.register()          # initial beat BEFORE the faults arm
        with faults.inject("elastic.heartbeat:error:times=3"):
            time.sleep(0.5)      # 3 injected misses + recovered beats
        try:
            assert watcher.alive_ranks() == [0]
            counters = monitor.snapshot()["counters"]
            assert counters["elastic.heartbeat_errors"] == 3
        finally:
            node.stop()


# ---------------------------------------------------------------------------
# serving: dispatch fault containment
# ---------------------------------------------------------------------------

class TestServingDispatchFault:
    def test_injected_dispatch_failure_contained_to_batch(self):
        from paddle_tpu.serving import EngineConfig, ServingEngine

        def predictor(x):
            return x * 2.0

        eng = ServingEngine(predictor, EngineConfig(
            max_batch_size=4, batch_timeout_ms=1.0, num_workers=1,
            warmup_on_start=False))
        eng.start()
        try:
            with faults.inject("serving.dispatch:error:times=1"):
                fut = eng.submit([np.ones((1, 4), np.float32)])
                with pytest.raises(faults.InjectedFault):
                    fut.result(timeout=30)
            assert eng.running                      # engine survived
            out = eng.submit([np.ones((1, 4), np.float32)]).result(
                timeout=30)
            np.testing.assert_allclose(out[0], 2.0 * np.ones((1, 4)))
            counters = monitor.snapshot()["counters"]
            assert counters["serving.failed"] >= 1
            assert counters["faults.injected.serving.dispatch"] == 1
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# disabled-path overhead guard + multi-fault soak
# ---------------------------------------------------------------------------

class TestOverheadGuard:
    def test_disabled_sites_record_nothing(self, ps_cluster):
        """With FLAGS_fault_inject unset, the seams never reach the
        registry: zero per-site bookkeeping after real PS traffic."""
        servers, client = ps_cluster
        faults.clear()           # drop hit counters from earlier tests
        assert faults._ENABLED is False
        client.pull_sparse("emb", [1, 2, 3])
        client.push_sparse("emb", [1], np.ones((1, 4), np.float32))
        assert faults.stats() == {}

    def test_disabled_gate_is_one_attribute_check(self):
        assert faults._ENABLED is False

        def gated():
            if faults._ENABLED:
                faults.check("x")

        def baseline():
            pass

        n = 20000
        gated(), baseline()                 # warm
        t0 = time.perf_counter()
        for _ in range(n):
            gated()
        t_gate = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            baseline()
        t_base = time.perf_counter() - t0
        # generous: anything near this bound means the disabled path
        # grew a lookup/allocation
        assert t_gate < 5.0 * t_base + 0.05, (t_gate, t_base)


@pytest.mark.slow
class TestMultiFaultSoak:
    def test_ps_soak_under_probabilistic_faults(self, ps_cluster):
        """Sustained pulls/pushes under seeded probabilistic resets on
        both RPC directions: every op lands exactly once."""
        servers, client = ps_cluster
        ids = np.arange(8, dtype=np.int64)
        base = client.pull_sparse("emb", ids).copy()
        n_push = 30
        with faults.inject("ps.rpc.send:conn_reset:p=0.05:seed=11;"
                           "ps.rpc.recv:conn_reset:p=0.05:seed=13"):
            for _ in range(n_push):
                client.push_sparse("emb", ids,
                                   np.ones((len(ids), 4), np.float32))
        after = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(after, base - 0.5 * n_push, rtol=1e-5)
        counters = monitor.snapshot()["counters"]
        assert counters["faults.injected"] > 0
        assert counters["ps.retries"] > 0
