import sys, glob
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, ".")
from paddle_tpu.kernels.flash_attention import _flash_core

bh, s, d = 12, 8192, 64
rng = np.random.RandomState(0)
q = jnp.asarray(rng.rand(bh, s, d).astype(np.float32) * 0.1).astype(jnp.bfloat16)
k = jnp.asarray(rng.rand(bh, s, d).astype(np.float32) * 0.1).astype(jnp.bfloat16)
v = jnp.asarray(rng.rand(bh, s, d).astype(np.float32) * 0.1).astype(jnp.bfloat16)
def loss(a, b, c):
    return (_flash_core(a, b, c, True, 512, 512, False).astype(jnp.float32) ** 2).sum()
g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
r = g(q, k, v); float(np.asarray(r[0].reshape(-1)[0]))
import os
os.makedirs("_trace2", exist_ok=True)
with jax.profiler.trace("_trace2"):
    for _ in range(5):
        r = g(q, k, v)
    float(np.asarray(r[0].reshape(-1)[0]))
