import sys
sys.path.insert(0, ".")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models.ernie import ErnieLayer
from paddle_tpu.jit import TrainStep

import paddle_tpu.nn.functional.attention as att
orig = att.scaled_dot_product_attention
def spy(q, k, v, **kw):
    print("SDPA q dtype:", q._value.dtype if hasattr(q, "_value") else q.dtype,
          "shape:", q.shape, flush=True)
    return orig(q, k, v, **kw)
att.scaled_dot_product_attention = spy
# ErnieSelfAttention imports inside forward: from ..nn.functional.attention import ...
h, ffn, heads, seq, batch = 512, 2048, 8, 2048, 1
net = ErnieLayer(h, heads, ffn, dropout=0.0)
x = paddle.to_tensor(np.random.rand(batch, seq, h).astype("float32") * 0.02)
from paddle_tpu.amp.state import *
opt = paddle.optimizer.SGD(parameters=net.parameters(), learning_rate=0.01)
step = TrainStep(net, lambda o: (o ** 2).mean(), opt, amp_dtype="bfloat16", n_model_inputs=1)
loss = step(x)
print("loss", float(loss))
