"""USER drive: round-3 inference changes (NHWC, bf16 export, dtype restore)."""
import os, sys, tempfile
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.jit import InputSpec, save, load
from paddle_tpu.inference import Config, create_predictor

# 1. NHWC ResNet == NCHW ResNet with same weights (user-facing equivalence)
paddle.seed(0)
nchw = models.resnet18(num_classes=8)
nhwc = models.resnet18(num_classes=8, data_format="NHWC")
nhwc.set_state_dict(nchw.state_dict())
nchw.eval(); nhwc.eval()
x = np.random.rand(2, 3, 64, 64).astype("float32")
d = np.abs(nchw(paddle.to_tensor(x)).numpy()
           - nhwc(paddle.to_tensor(x.transpose(0, 2, 3, 1))).numpy()).max()
assert d < 2e-4, f"NHWC != NCHW: {d}"
print("1. NHWC/NCHW equivalence OK", d)

# 2. bf16 export -> predictor run -> close to fp32 eager; artifact actually bf16
td = tempfile.mkdtemp()
p = os.path.join(td, "m_bf16")
save(nhwc, p, input_spec=[InputSpec([2, 64, 64, 3], "float32")], precision="bfloat16")
cfg = Config(p); cfg.enable_tensorrt_engine(precision_mode="bfloat16")
pred = create_predictor(cfg)
h = pred.get_input_handle(pred.get_input_names()[0])
h.copy_from_cpu(x.transpose(0, 2, 3, 1))
import jax.numpy as jnp
assert pred._feeds[pred.get_input_names()[0]].dtype == jnp.bfloat16, "feed not cast at copy_from_cpu"
pred.run()
out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
ref = nchw(paddle.to_tensor(x)).numpy()
assert out.dtype == np.float32, out.dtype
rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
assert rel < 0.1, f"bf16 predictor too far from fp32 eager: {rel}"
print("2. bf16 export + predictor OK, rel err", round(float(rel), 4))

# 3. reload artifact fresh (bf16 params restored from npz void blobs)
tl = load(p)
sd = tl.state_dict()
some = next(iter(sd.values()))
assert some._value.dtype == jnp.bfloat16, some._value.dtype
y2 = tl(paddle.to_tensor(x.transpose(0, 2, 3, 1).astype(np.float32)).astype("bfloat16"))
print("3. jit.load bf16 dtype restore OK")

# 4. fp32 save path unchanged (no precision kwarg), old-artifact compat
p2 = os.path.join(td, "m_fp32")
save(nhwc, p2, input_spec=[InputSpec([2, 64, 64, 3], "float32")])
cfg2 = Config(p2)
pred2 = create_predictor(cfg2)
out2 = pred2.run([paddle.to_tensor(x.transpose(0, 2, 3, 1))])[0].numpy()
assert np.abs(out2 - ref).max() < 2e-4
print("4. fp32 save/predict unchanged OK")

# 5. error path: predictor on missing model
try:
    create_predictor(Config(os.path.join(td, "nope")))
    raise SystemExit("expected NotFoundError")
except Exception as e:
    assert "Cannot open model file" in str(e), e
print("5. missing-model error path OK")

# 6. data_format survives save->load meta roundtrip for vgg/mobilenet untouched models
m = models.mobilenet_v2(num_classes=4) if hasattr(models, "mobilenet_v2") else models.vgg16(num_classes=4)
m.eval()
print("6. other vision models still construct OK")
print("ALL VERIFY DRIVES PASSED")
