"""USER drive: a CTR-serving-style workflow over the deepened PS tier."""
import os, sys, tempfile
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.ps import PsServer, PsClient, Communicator, DistributedEmbedding
from paddle_tpu.distributed.ps.table import SSDSparseTable

# a realistic CTR loop: 2 servers, adam+ctr sparse table, show/click feed,
# nightly decay+shrink, then an SSD-spill table holding more rows than RAM cap
servers = [PsServer() for _ in range(2)]
tables = []
for s in servers:
    tables.append(s.add_sparse_table("ctr", dim=8, optimizer="adam", lr=0.05,
                                     accessor="ctr", delete_threshold=0.5,
                                     ttl_days=7))
    s.run()
client = PsClient([f"{s.host}:{s.port}" for s in servers])
client.register_sparse_dim("ctr", 8)
comm = Communicator(client)
emb = DistributedEmbedding(client, "ctr", dim=8, communicator=comm)
paddle.seed(0)
head = nn.Linear(16, 2)
opt = paddle.optimizer.Adam(parameters=head.parameters(), learning_rate=0.05)
ce = nn.CrossEntropyLoss()
rng = np.random.default_rng(0)
ids = rng.integers(0, 100, (32, 2))
y = paddle.to_tensor((ids.sum(1) % 2).astype(np.int32))
losses = []
for _ in range(12):
    e = emb(paddle.to_tensor(ids))
    loss = ce(head(e.reshape([32, 16])), y)
    loss.backward(); opt.step(); opt.clear_grad(); comm.flush()
    losses.append(float(loss))
assert losses[-1] < losses[0] * 0.8, losses
print("1. CTR train through adam PS descends:", round(losses[0], 3), "->", round(losses[-1], 3))

# show/click stats + nightly maintenance on the server tables
for t in tables:
    seen = list(t._rows)[:5]
    if seen:
        t.push_show_click(seen, [5.0] * len(seen), [1.0] * len(seen))
n_before = sum(len(t) for t in tables)
for t in tables:
    for _ in range(8):       # 8 decay cycles > ttl 7 for never-re-seen rows
        t.decay()
evicted = sum(t.shrink() for t in tables)
assert evicted > 0
print(f"2. nightly decay+shrink evicted {evicted}/{n_before} rows")
comm.stop(); client.close()
for s in servers:
    s.stop()

# SSD-spill tier
td = tempfile.mkdtemp()
t = SSDSparseTable(dim=4, path=os.path.join(td, "big"), cache_rows=8,
                   optimizer="lazy_adam", lr=0.1, seed=2)
all_ids = list(range(50))
rows = t.pull(all_ids)
assert t.resident_rows <= 8 and len(t) == 50
t.push(all_ids[:3], np.ones((3, 4), np.float32))
rows2 = t.pull(all_ids)
assert not np.allclose(rows2[:3], rows[:3]) and np.allclose(rows2[10:], rows[10:])
print("3. SSD spill table: 50 rows, <=8 resident, updates correct across spill")
t.close()
print("ALL VERIFY DRIVES PASSED")
