import sys, threading, time, tempfile, textwrap, pathlib
sys.path.insert(0, "/root/repo")
from paddle_tpu._native import TCPStore
from paddle_tpu.parallel.elastic import ElasticManager, launch_elastic

tmp_path = pathlib.Path(tempfile.mkdtemp())
script = tmp_path / "train.py"
script.write_text(textwrap.dedent(f"""
    import json, os, sys, time
    sys.path.insert(0, "/root/repo")
    from paddle_tpu.framework.sharded_io import AutoCheckpoint
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    ws = int(os.environ["PADDLE_TRAINERS_NUM"])
    launch = int(os.environ["PADDLE_ELASTIC_RESTART_COUNT"])
    log = open({str(tmp_path)!r} + f"/log_{{rank}}.txt", "a")
    print(f"START rank{{rank}} ws{{ws}} launch{{launch}}", file=log, flush=True)
    if rank == 1 and launch == 0:
        time.sleep(0.4)
        sys.exit(9)
    if rank == 0:
        state = {{}}
        acp = AutoCheckpoint({str(tmp_path)!r} + "/ckpt",
            save_fn=lambda p: open(p, "w").write(json.dumps(state)),
            load_fn=lambda p: state.update(json.loads(open(p).read())))
        for epoch in acp.train_epoch_range(8):
            state["epoch"] = epoch
            print(f"ws{{ws}} epoch{{epoch}}", file=log, flush=True)
            time.sleep(0.35)
    else:
        time.sleep(0.35 * 8)
    sys.exit(0)
"""))
store = TCPStore("127.0.0.1", 0, is_master=True)
def join_later():
    time.sleep(2.0)
    ElasticManager(store, rank=-1, world_size=0).announce_join("n")
th = threading.Thread(target=join_later); th.start()
res = launch_elastic(str(script), nprocs=2, max_restarts=2, timeout=120,
                     store=store, max_np=3)
th.join()
print("restarts:", res.restarts, "rcs:", res.returncodes)
print(open(tmp_path / "log_0.txt").read())
