"""USER drive: CE logsumexp path + ErnieForPretraining changes."""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(0)
logits = rng.randn(6, 11).astype("float32") * 3
labels = rng.randint(0, 11, (6,)).astype("int64")
labels[2] = -100  # ignore_index
w = rng.rand(11).astype("float32") + 0.5

def ref_ce(logits, labels, weight=None, smoothing=0.0, reduction="mean"):
    lp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) - logits.max(-1, keepdims=True)
    per, ws = [], []
    for i, l in enumerate(labels):
        if l == -100:
            per.append(0.0); ws.append(0.0); continue
        p = -lp[i, l]
        if smoothing > 0:
            p = (1 - smoothing) * p + smoothing * (-lp[i].mean())
        cw = weight[l] if weight is not None else 1.0
        per.append(p * cw); ws.append(cw)
    per = np.array(per)
    if reduction == "mean":
        return per.sum() / (np.sum(ws) if weight is not None else max((labels != -100).sum(), 1))
    if reduction == "sum":
        return per.sum()
    return per

for kw, refkw in [
    (dict(), dict()),
    (dict(label_smoothing=0.1), dict(smoothing=0.1)),
    (dict(weight=paddle.to_tensor(w)), dict(weight=w)),
    (dict(reduction="sum"), dict(reduction="sum")),
    (dict(reduction="none"), dict(reduction="none")),
]:
    got = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels), **kw).numpy()
    want = ref_ce(logits, labels, **refkw)
    assert np.allclose(got, want, atol=1e-5), (kw, got, want)
print("1. cross_entropy hard-label variants match manual reference")

# soft label unchanged
soft = rng.rand(6, 11).astype("float32"); soft /= soft.sum(-1, keepdims=True)
got = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(soft), soft_label=True).numpy()
lp = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True))
want = np.mean((lp.squeeze(-1) + logits.max(-1)) - (soft * logits).sum(-1))
assert abs(got - want) < 1e-4, (got, want)
print("2. soft-label CE unchanged")

# grad correctness of the lse path: d/dlogits = softmax - onehot
t = paddle.to_tensor(logits, stop_gradient=False)
loss = F.cross_entropy(t, paddle.to_tensor(np.array([1, 2, 3, 4, 5, 6]).astype("int64")), reduction="sum")
loss.backward()
sm = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
oh = np.zeros_like(logits); oh[np.arange(6), [1, 2, 3, 4, 5, 6]] = 1
assert np.allclose(np.asarray(t.grad), sm - oh, atol=1e-5)
print("3. CE gradient = softmax - onehot")

# ErnieForPretraining end-to-end: logits shape + finite loss + one train step
from paddle_tpu import models
from paddle_tpu.jit import TrainStep
base = models.ErnieModel(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, intermediate_size=64, hidden_dropout_prob=0.0)
net = models.ErnieForPretraining(base)
ids = paddle.to_tensor(rng.randint(0, 64, (2, 8)).astype("int32"))
logits_t, nsp = net(ids)
assert tuple(logits_t.shape) == (2, 8, 64), logits_t.shape
ce = nn.CrossEntropyLoss()
def loss_fn(logits, nsp_logits, ids, nspl):
    return ce(logits.reshape([-1, logits.shape[-1]]), ids.reshape([-1])) + ce(nsp_logits, nspl)
opt = paddle.optimizer.AdamW(parameters=net.parameters(), learning_rate=1e-3)
step = TrainStep(net, loss_fn, opt, amp_dtype="bfloat16", n_model_inputs=1)
nspl = paddle.to_tensor(rng.randint(0, 2, (2,)).astype("int32"))
l0 = float(step(ids, ids, nspl))
for _ in range(5):
    l = float(step(ids, ids, nspl))
assert np.isfinite(l) and l < l0, (l0, l)
print("4. ErnieForPretraining train step descends:", round(l0, 3), "->", round(l, 3))
print("ALL VERIFY DRIVES PASSED")
