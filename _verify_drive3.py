"""USER drive: flash attention public API numerics after kernel rewrite."""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, "/root/repo")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import paddle_tpu as paddle
from paddle_tpu.kernels.flash_attention import flash_attention, _reference_bhsd

rng = np.random.RandomState(0)
B, S, H, D = 2, 256, 4, 64

def ref_attn(q, k, v, causal):
    # independent numpy oracle
    qf = q.transpose(0, 2, 1, 3).astype(np.float64)
    kf = k.transpose(0, 2, 1, 3).astype(np.float64)
    vf = v.transpose(0, 2, 1, 3).astype(np.float64)
    s = np.einsum("bhsd,bhtd->bhst", qf, kf) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhst,bhtd->bhsd", p, vf).transpose(0, 2, 1, 3)

for dtype, tol, gtol in (("float32", 2e-5, 2e-3), ("bfloat16", 2e-2, 5e-2)):
    for causal in (False, True):
        q = (rng.rand(B, S, H, D).astype("float32") - 0.5)
        k = (rng.rand(B, S, H, D).astype("float32") - 0.5)
        v = (rng.rand(B, S, H, D).astype("float32") - 0.5)
        qt = paddle.to_tensor(q).astype(dtype); qt.stop_gradient = False
        kt = paddle.to_tensor(k).astype(dtype); kt.stop_gradient = False
        vt = paddle.to_tensor(v).astype(dtype); vt.stop_gradient = False
        out = flash_attention(qt, kt, vt, causal=causal, block_q=128, block_k=128)
        want = ref_attn(q, k, v, causal)
        err = np.abs(np.asarray(out._value, dtype=np.float64) - want).max()
        assert err < tol, (dtype, causal, err)
        # grads: compare vs jax fused reference grads
        loss = (out.astype("float32") ** 2).sum()
        loss.backward()
        def ref_loss(a, b, c):
            bh = B * H
            qq = jnp.swapaxes(a, 1, 2).reshape(bh, S, D)
            kk = jnp.swapaxes(b, 1, 2).reshape(bh, S, D)
            vv = jnp.swapaxes(c, 1, 2).reshape(bh, S, D)
            o = _reference_bhsd(qq, kk, vv, causal)
            return (o.astype(jnp.float32) ** 2).sum()
        gq, gk, gv = jax.grad(ref_loss, argnums=(0, 1, 2))(
            jnp.asarray(q).astype(dtype), jnp.asarray(k).astype(dtype), jnp.asarray(v).astype(dtype))
        for got, wantg, nm in ((qt.grad, gq, "dq"), (kt.grad, gk, "dk"), (vt.grad, gv, "dv")):
            ga = np.asarray(got, dtype=np.float64)
            wa = np.asarray(wantg, dtype=np.float64)
            rel = np.abs(ga - wa).max() / (np.abs(wa).max() + 1e-9)
            assert rel < gtol, (dtype, causal, nm, rel)
        print(f"{dtype} causal={causal}: out_err={err:.2e} grads OK")

# ragged fallback still works (S not divisible by block)
q = paddle.to_tensor(rng.rand(1, 100, 2, 32).astype("float32"))
out = flash_attention(q, q, q, causal=True)
assert tuple(out.shape) == (1, 100, 2, 32)
print("ragged-length fallback OK")
print("ALL VERIFY DRIVES PASSED")
