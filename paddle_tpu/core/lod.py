"""LoDTensor — ragged sequences as padded-dense + lengths (TPU policy).

Reference parity: LoD (level-of-detail) tensors
(`paddle/fluid/framework/tensor.h`, LoD utils `phi/core/lod_utils.h`,
python `fluid.create_lod_tensor`): a flat value buffer + offset table
describing ragged sequence boundaries, consumed by `operators/sequence_ops/`.

TPU-native redesign: XLA wants static shapes, so raggedness is carried as
(padded dense data [B, T, ...], lengths [B]) with a bucketing policy that
pads T up to a bounded set of bucket boundaries — the executor-cache-key
answer to dynamic shapes (SURVEY §7 hard part 1: "LoD/ragged ops need a
bucketing/padding policy baked into the cache key"). Compute stays dense
and masked — MXU-friendly — and every sequence op is a fused jnp program.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp


DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def bucket_length(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= n: bounds the set of padded shapes (and thus the
    XLA executable cache size) regardless of input length distribution."""
    for b in buckets:
        if n <= b:
            return b
    return int(n)  # beyond the table: pad exactly (rare, still compiles)


class LoDTensor:
    """Ragged batch: `data` [B, T, ...] padded dense + `lengths` [B]."""

    def __init__(self, data, lengths):
        self.data = jnp.asarray(data)
        self.lengths = jnp.asarray(lengths, jnp.int32)
        if self.data.shape[0] != self.lengths.shape[0]:
            raise ValueError(
                f"batch mismatch: data {self.data.shape[0]} vs "
                f"lengths {self.lengths.shape[0]}")

    @property
    def shape(self):
        return list(self.data.shape)

    def recursive_sequence_lengths(self) -> List[List[int]]:
        """Reference LoDTensor API (one ragged level)."""
        return [[int(l) for l in np.asarray(self.lengths)]]

    def lod(self) -> List[List[int]]:
        """Offset form: [0, l0, l0+l1, ...] (framework LoD convention)."""
        off = np.concatenate([[0], np.cumsum(np.asarray(self.lengths))])
        return [[int(o) for o in off]]

    def mask(self, dtype=jnp.float32):
        """[B, T] validity mask."""
        t = self.data.shape[1]
        return (jnp.arange(t)[None, :] < self.lengths[:, None]).astype(dtype)

    def to_list(self) -> List[np.ndarray]:
        d = np.asarray(self.data)
        return [d[i, :int(l)] for i, l in enumerate(np.asarray(self.lengths))]


def create_lod_tensor(seqs: Sequence, buckets: Sequence[int] = DEFAULT_BUCKETS,
                      pad_value=0.0) -> LoDTensor:
    """Build from a list of variable-length arrays, padding T to the bucket
    boundary (fluid.create_lod_tensor role, plus the padding policy)."""
    seqs = [np.asarray(s) for s in seqs]
    if not seqs:
        raise ValueError("empty sequence list")
    lengths = [len(s) for s in seqs]
    t = bucket_length(max(lengths), buckets)
    trailing = seqs[0].shape[1:]
    out = np.full((len(seqs), t) + trailing, pad_value, seqs[0].dtype)
    for i, s in enumerate(seqs):
        out[i, :len(s)] = s
    return LoDTensor(out, np.asarray(lengths, np.int32))
