"""Shims over jax API drift, pinned to the container's jax.

The codebase targets the current jax surface; where the installed wheel
predates a rename, the old spelling is bridged here so call sites stay
modern. Covered:
  - `lax.axis_size(name)` (newer jax) vs `lax.psum(1, name)` (0.4.x) —
    psum of a unit literal is constant-folded to the axis size (an int)
    and raises NameError when the axis is unbound, matching axis_size.
"""
from __future__ import annotations

from jax import lax

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:
    def axis_size(axis_name):
        return lax.psum(1, axis_name)
