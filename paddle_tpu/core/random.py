"""Seeded RNG management.

Reference parity: `paddle/fluid/framework/generator.cc` / `phi/core/generator.h`
(global + per-device Philox generators, `paddle.seed`). TPU-first design: a
stateful key-splitting `Generator` over `jax.random` (threefry/rbg), so eager
ops draw fresh keys while jitted programs take keys as explicit inputs.
"""
from __future__ import annotations

import functools
import threading

import jax


class TraceKeyError(RuntimeError):
    """A stateful key draw was attempted inside a jax trace with no trace
    key pushed. Mutating the global generator under a trace would leak a
    tracer into host state; callers must hoist `next_key()` out of traced
    fns (or push a trace key). The eager dispatch cache treats this as a
    bailout signal and reruns the op uncached (core/autograd.py)."""


class Generator:
    """Stateful wrapper over a jax PRNG key; `next_key()` splits off fresh keys."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            # Key creation is LAZY: materializing a PRNG key initializes the
            # XLA backend, which must not happen at import time (it would
            # forbid a later jax.distributed.initialize in multi-process
            # bring-up).
            self._key = None
            self._count = 0
            self._pool = []
        return self

    def initial_seed(self) -> int:
        return self._seed

    _POOL = 16

    @staticmethod
    @functools.lru_cache(maxsize=1)
    def _refill_fn(n):
        # ONE jitted executable producing n sequential split(k, 2) draws —
        # bitwise the same stream as n individual next_key calls (the
        # chain advances split[0], hands out split[1]), amortizing the
        # per-draw device dispatch to 1/n
        def chain(k):
            def body(c, _):
                c2, out = jax.random.split(c)
                return c2, out
            return jax.lax.scan(body, k, None, length=n)
        return jax.jit(chain)

    def _refill(self):
        cur = self._key if self._key is not None \
            else jax.random.key(self._seed)
        new_key, pool = Generator._refill_fn(self._POOL)(cur)
        if isinstance(new_key, jax.core.Tracer):
            # a jit trace would capture the split and leak a tracer
            # into host state (note: nothing is committed before this
            # raise — a lazily-created key may itself be a tracer);
            # vjp-linearize replays (recompute) keep concrete keys
            # concrete and pass through here
            raise TraceKeyError(
                "Generator.next_key() called inside a jax trace — draw "
                "the key before tracing (or push a trace key for replay)")
        self._key = new_key
        self._pool = list(pool)

    @staticmethod
    def _trace_mode() -> str:
        """"clean" (no trace: pool OK), "staging" (jit/pjit: must raise),
        or "unknown" (linearize/other/probe failure: fall back to the
        pre-pool BEHAVIORAL path — split once and inspect the result — so
        a jax upgrade that breaks the private probe degrades to the old
        per-draw safety, never to silently baking a key constant)."""
        try:
            from jax._src import core as _core
            if _core.trace_state_clean():
                return "clean"
            if type(_core.trace_ctx.trace).__name__ == "DynamicJaxprTrace":
                return "staging"
        except Exception:
            pass
        return "unknown"

    def next_key(self, n: int = 1):
        # keys are drawn from a small pre-split POOL: one device-side
        # split serves 16 draws. On a high-latency dispatch path (the
        # tunneled chip) a per-draw split costs one RTT — with two
        # captured static programs per eager step that was ~20% of the
        # whole step. get_state snapshots the pool so restore stays EXACT.
        mode = self._trace_mode()
        if mode == "staging":
            # the pre-pool code raised on EVERY staged-trace draw (the
            # split produced a tracer); a warm pool must not weaken that
            # to a 1-in-16 intermittent — a concrete key baked into a
            # traced program would replay the same randomness every call
            raise TraceKeyError(
                "Generator.next_key() called inside a jax trace — draw "
                "the key before tracing (or push a trace key for replay)")
        if mode == "unknown":
            # behavioral pre-pool path: per-draw split whose RESULT tells
            # us whether this trace stages (tracer -> raise) or replays
            # concretely (linearize recompute -> serve). The pool stream
            # is preserved: these draws consume pool slots first.
            with self._lock:
                keys = []
                for _ in range(n):
                    if self._pool:
                        keys.append(self._pool.pop(0))
                        continue
                    cur = self._key if self._key is not None \
                        else jax.random.key(self._seed)
                    new_key, k = jax.random.split(cur)
                    if isinstance(new_key, jax.core.Tracer):
                        raise TraceKeyError(
                            "Generator.next_key() called inside a jax "
                            "trace — draw the key before tracing (or push "
                            "a trace key for replay)")
                    self._key = new_key
                    keys.append(k)
                self._count += n
            return keys[0] if n == 1 else keys
        with self._lock:
            keys = []
            for _ in range(n):
                if not self._pool:
                    self._refill()
                keys.append(self._pool.pop(0))
            self._count += n
        return keys[0] if n == 1 else keys

    def get_state(self):
        """(seed, count, raw key data, pooled key data) — the raw key +
        remaining pool make restore EXACT: replaying `count` draws can't
        reproduce a stream whose draws had mixed granularity
        (split(k, n+1) != n sequential split(k, 2))."""
        import numpy as np
        with self._lock:  # consistent (count, key, pool) snapshot
            kd = None if self._key is None else \
                np.asarray(jax.random.key_data(self._key))
            pool = tuple(np.asarray(jax.random.key_data(k))
                         for k in getattr(self, "_pool", ()))
            return (self._seed, self._count, kd, pool)

    def set_state(self, state):
        if len(state) == 2:  # legacy (seed, count) form: replay draws
            seed, count = state
            self.manual_seed(seed)
            if count:
                self.next_key(count)
            return
        seed, count, kd = state[0], state[1], state[2]
        pool = state[3] if len(state) > 3 else ()
        with self._lock:
            self._seed = int(seed)
            self._count = int(count)
            self._key = None if kd is None else \
                jax.random.wrap_key_data(jax.numpy.asarray(kd))
            self._pool = [jax.random.wrap_key_data(jax.numpy.asarray(p))
                          for p in pool]


_DEFAULT = Generator(0)

# Trace-time key stack: when a jitted/static program is being traced,
# `jit` pushes a traced key here so stateful eager RNG entry points
# (dropout etc.) split from the *traced* key instead of baking a constant.
_TRACE_KEYS = []


def push_trace_key(key):
    _TRACE_KEYS.append(key)


def pop_trace_key():
    return _TRACE_KEYS.pop()


def in_trace() -> bool:
    return bool(_TRACE_KEYS)


def seed(s: int) -> Generator:
    """paddle.seed parity: reseed the global generator."""
    _DEFAULT.manual_seed(s)
    return _DEFAULT


def default_generator() -> Generator:
    return _DEFAULT


def next_key(n: int = 1):
    if _TRACE_KEYS:
        import jax
        k = _TRACE_KEYS[-1]
        _TRACE_KEYS[-1], *keys = jax.random.split(k, n + 1)
        return keys[0] if n == 1 else keys
    return _DEFAULT.next_key(n)


def get_rng_state():
    return _DEFAULT.get_state()


def set_rng_state(state):
    _DEFAULT.set_state(state)
