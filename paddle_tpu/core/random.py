"""Seeded RNG management.

Reference parity: `paddle/fluid/framework/generator.cc` / `phi/core/generator.h`
(global + per-device Philox generators, `paddle.seed`). TPU-first design: a
stateful key-splitting `Generator` over `jax.random` (threefry/rbg), so eager
ops draw fresh keys while jitted programs take keys as explicit inputs.
"""
from __future__ import annotations

import threading

import jax


class TraceKeyError(RuntimeError):
    """A stateful key draw was attempted inside a jax trace with no trace
    key pushed. Mutating the global generator under a trace would leak a
    tracer into host state; callers must hoist `next_key()` out of traced
    fns (or push a trace key). The eager dispatch cache treats this as a
    bailout signal and reruns the op uncached (core/autograd.py)."""


class Generator:
    """Stateful wrapper over a jax PRNG key; `next_key()` splits off fresh keys."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            # Key creation is LAZY: materializing a PRNG key initializes the
            # XLA backend, which must not happen at import time (it would
            # forbid a later jax.distributed.initialize in multi-process
            # bring-up).
            self._key = None
            self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self, n: int = 1):
        with self._lock:
            cur = self._key if self._key is not None \
                else jax.random.key(self._seed)
            new_key, *keys = jax.random.split(cur, n + 1)
            if isinstance(new_key, jax.core.Tracer):
                # a jit trace would capture the split and leak a tracer
                # into host state (note: nothing is committed before this
                # raise — a lazily-created key may itself be a tracer);
                # vjp-linearize replays (recompute) keep concrete keys
                # concrete and pass through here
                raise TraceKeyError(
                    "Generator.next_key() called inside a jax trace — draw "
                    "the key before tracing (or push a trace key for replay)")
            self._key = new_key
            self._count += n
        return keys[0] if n == 1 else keys

    def get_state(self):
        """(seed, count, raw key data) — the raw key makes restore EXACT:
        replaying `count` draws can't reproduce a stream whose draws had
        mixed granularity (split(k, n+1) != n sequential split(k, 2))."""
        import numpy as np
        with self._lock:  # consistent (count, key) snapshot
            kd = None if self._key is None else \
                np.asarray(jax.random.key_data(self._key))
            return (self._seed, self._count, kd)

    def set_state(self, state):
        if len(state) == 2:  # legacy (seed, count) form: replay draws
            seed, count = state
            self.manual_seed(seed)
            if count:
                self.next_key(count)
            return
        seed, count, kd = state
        with self._lock:
            self._seed = int(seed)
            self._count = int(count)
            self._key = None if kd is None else \
                jax.random.wrap_key_data(jax.numpy.asarray(kd))


_DEFAULT = Generator(0)

# Trace-time key stack: when a jitted/static program is being traced,
# `jit` pushes a traced key here so stateful eager RNG entry points
# (dropout etc.) split from the *traced* key instead of baking a constant.
_TRACE_KEYS = []


def push_trace_key(key):
    _TRACE_KEYS.append(key)


def pop_trace_key():
    return _TRACE_KEYS.pop()


def in_trace() -> bool:
    return bool(_TRACE_KEYS)


def seed(s: int) -> Generator:
    """paddle.seed parity: reseed the global generator."""
    _DEFAULT.manual_seed(s)
    return _DEFAULT


def default_generator() -> Generator:
    return _DEFAULT


def next_key(n: int = 1):
    if _TRACE_KEYS:
        import jax
        k = _TRACE_KEYS[-1]
        _TRACE_KEYS[-1], *keys = jax.random.split(k, n + 1)
        return keys[0] if n == 1 else keys
    return _DEFAULT.next_key(n)


def get_rng_state():
    return _DEFAULT.get_state()


def set_rng_state(state):
    _DEFAULT.set_state(state)
