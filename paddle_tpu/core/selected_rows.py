"""SelectedRows — sparse row-set gradients (embedding backward).

Reference parity: `phi/core/selected_rows.h` / `framework/selected_rows_utils.h`
(rows + value block + height), produced by lookup-table grad kernels and
consumed by sparse optimizer kernels (`operators/optimizers/` sparse adam/
sgd paths with merged duplicate rows).

TPU-native: rows/values are device arrays; `merge()` fuses duplicate ids
with a segment-sum (one XLA scatter-add); densify only when an optimizer
has no sparse rule. For a [vocab, dim] embedding touched by B ids, grads
carry B*dim floats instead of vocab*dim — the HBM/dispatch win the
reference gets from SelectedRows.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


class SelectedRows:
    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"rows {self.rows.shape[0]} != values rows "
                f"{self.values.shape[0]}")

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def merge(self) -> "SelectedRows":
        """Sum duplicate row ids (merge_selected_rows op role)."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True)
        summed = jnp.zeros((uniq.shape[0],) + self.values.shape[1:],
                           self.values.dtype).at[inv].add(self.values)
        return SelectedRows(uniq, summed, self.height)

    def to_dense(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.rows].add(self.values)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(jnp.concatenate([self.rows, other.rows]),
                                jnp.concatenate([self.values, other.values]),
                                self.height)
        return self.to_dense() + other

    __radd__ = __add__

    def __repr__(self):
        return (f"SelectedRows(n_rows={self.rows.shape[0]}, "
                f"height={self.height}, dim={self.values.shape[1:]})")
