"""paddle_tpu.Tensor — imperative tensor over a `jax.Array`.

Reference parity: the dygraph `VarBase`/`VariableWrapper`
(`paddle/fluid/imperative/layer.h`) + python Tensor surface
(`python/paddle/fluid/framework.py:1098` Variable and the monkey-patched
varbase methods). TPU-first: the payload is a `jax.Array` living on the XLA
backend; during `to_static` tracing the payload may be a JAX tracer — every
op accepts either transparently.

The full op method surface (``t.sum()``, ``t.reshape(...)`` …) is attached by
``paddle_tpu.ops._bind_tensor_methods`` at package import, mirroring Paddle's
``monkey_patch_varbase``.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from .dtype import convert_dtype, get_default_dtype
from .place import get_place, CPUPlace

_ops = None  # set by paddle_tpu.ops at import time (monkey_patch_varbase parity)

# payload types accepted verbatim (no jnp.asarray); ops.lazy extends this
# with its pending _LazyValue at import — the FLAGS_lazy_eager deferred
# payload rides the same isinstance check the eager path already pays
_VALUE_TYPES = (jax.Array, jax.core.Tracer)


class Tensor:
    __slots__ = ("_value", "stop_gradient", "grad", "_node", "name", "persistable",
                 "_hooks", "dist_attr", "process_mesh")

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, _VALUE_TYPES):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self.name = name
        self.persistable = False
        self._hooks = []
        self.dist_attr = None  # PartitionSpec-like tuple for SPMD placement
        self.process_mesh = None  # auto_parallel ProcessMesh annotation

    # ---- metadata ----
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        try:
            dev = list(self._value.devices())[0]
            return get_place().__class__(getattr(dev, "id", 0)) if dev.platform != "cpu" else CPUPlace(0)
        except Exception:
            return get_place()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def numel(self) -> int:
        return self.size

    # ---- conversion ----
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self, *idx):
        if idx:
            return self.numpy().item(*idx)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype) -> "Tensor":
        return _ops.cast(self, dtype)

    def cast(self, dtype) -> "Tensor":
        return _ops.cast(self, dtype)

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient, name=self.name)

    def pin_memory(self) -> "Tensor":
        return self

    def clone(self) -> "Tensor":
        return _ops.assign(self)

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def block_until_ready(self):
        if hasattr(self._value, "block_until_ready"):
            self._value.block_until_ready()
        return self

    # ---- autograd ----
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        autograd.backward(self, grad_tensor, retain_graph=retain_graph)

    def gradient(self):
        return None if self.grad is None else np.asarray(
            self.grad._value if isinstance(self.grad, Tensor) else self.grad)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def zero_(self):
        # jnp.asarray resolves a pending lazy payload (FLAGS_lazy_eager)
        # before zeros_like reads its dtype; concrete arrays pass through
        self._value = jnp.zeros_like(jnp.asarray(self._value))
        return self

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def set_value(self, value):
        """In-place payload replacement (keeps shape/dtype contract like Paddle)."""
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}")
        self._value = value.astype(self._value.dtype)
        return self

    def get_tensor(self):  # LoDTensor accessor parity
        return self

    # ---- operators (full surface bound by ops._bind_tensor_methods) ----
    def __add__(self, o):
        return _ops.add(self, o)

    def __radd__(self, o):
        return _ops.add(self, o)

    def __sub__(self, o):
        return _ops.subtract(self, o)

    def __rsub__(self, o):
        return _ops.subtract(o, self)

    def __mul__(self, o):
        return _ops.multiply(self, o)

    def __rmul__(self, o):
        return _ops.multiply(self, o)

    def __truediv__(self, o):
        return _ops.divide(self, o)

    def __rtruediv__(self, o):
        return _ops.divide(o, self)

    def __floordiv__(self, o):
        return _ops.floor_divide(self, o)

    def __rfloordiv__(self, o):
        return _ops.floor_divide(o, self)

    def __mod__(self, o):
        return _ops.remainder(self, o)

    def __pow__(self, o):
        return _ops.pow(self, o)

    def __rpow__(self, o):
        return _ops.pow(o, self)

    def __matmul__(self, o):
        return _ops.matmul(self, o)

    def __rmatmul__(self, o):
        return _ops.matmul(o, self)

    def __neg__(self):
        return _ops.scale(self, -1.0)

    def __abs__(self):
        return _ops.abs(self)

    def __invert__(self):
        return _ops.logical_not(self)

    def __eq__(self, o):  # noqa: E721  (tensor semantics, like Paddle)
        return _ops.equal(self, o)

    def __ne__(self, o):
        return _ops.not_equal(self, o)

    def __lt__(self, o):
        return _ops.less_than(self, o)

    def __le__(self, o):
        return _ops.less_equal(self, o)

    def __gt__(self, o):
        return _ops.greater_than(self, o)

    def __ge__(self, o):
        return _ops.greater_equal(self, o)

    def __hash__(self):
        return id(self)

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        return bool(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __getitem__(self, idx):
        return _ops.getitem(self, idx)

    def __setitem__(self, idx, value):
        return _ops.setitem_(self, idx, value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            body = np.array2string(self.numpy(), precision=4, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"place={get_place()}, stop_gradient={sg},\n       {body})")

    # numpy interop
    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a


class Parameter(Tensor):
    """Trainable tensor (stop_gradient=False, persistable). Parity:
    `python/paddle/fluid/framework.py` Parameter / ParamBase."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True


def _maybe_wrap(x, stop_gradient=True):
    return x if isinstance(x, Tensor) else Tensor(x, stop_gradient=stop_gradient)


# jax pytree registration so Tensors can cross jit boundaries transparently
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._value,), (t.stop_gradient, t.name)),
    lambda aux, ch: Tensor(ch[0], stop_gradient=aux[0], name=aux[1]),
)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t._value,), (t.name, t.trainable)),
    lambda aux, ch: Parameter(ch[0], name=aux[0], trainable=aux[1]),
)
