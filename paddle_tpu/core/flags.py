"""Global exported-flags registry.

Reference parity: `paddle/fluid/platform/flags.cc:48` (PADDLE_DEFINE_EXPORTED_*)
+ `pybind/global_value_getter_setter.cc` + `paddle.set_flags/get_flags`.
Flags may also be seeded from environment variables named FLAGS_<name>.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List

_REGISTRY: Dict[str, Any] = {}

# flag-name -> callbacks fired on set_flags (lets hot paths cache a flag in
# a module attribute — e.g. paddle_tpu.monitor._ENABLED — instead of paying
# a dict lookup per op; the reference's equivalent is the exported-flag
# pointer that C++ call sites read directly)
_WATCHERS: Dict[str, List[Callable[[Any], None]]] = {}


def watch_flag(name: str, fn: Callable[[Any], None]) -> None:
    """Register fn(new_value) to run whenever `name` is set via set_flags."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown flag {name}")
    _WATCHERS.setdefault(name, []).append(fn)


def define_flag(name: str, default: Any, doc: str = "") -> None:
    env = os.environ.get(f"FLAGS_{name}")
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes", "on")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = value


def set_flags(flags: Dict[str, Any]) -> None:
    for k, v in flags.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag {k}")
        _REGISTRY[key] = v
        for fn in _WATCHERS.get(key, ()):
            fn(v)


def get_flags(flags) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k[6:] if k.startswith("FLAGS_") else k
        out[f"FLAGS_{key}"] = _REGISTRY[key]
    return out


def flag(name: str) -> Any:
    return _REGISTRY[name]


# ---- core flags (names kept from the reference where they exist) ----
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf (operator.cc:1171)")
define_flag("eager_auto_jit", True,
            "promote a repeatedly-called top-level Layer to its captured "
            "static program (step-chain capture: one executable per fwd "
            "and per bwd instead of per-op dispatch)")
define_flag("use_standalone_executor", True, "new-executor opt-in (executor.py:1392)")
define_flag("eager_delete_tensor_gb", 0.0, "GC threshold (unused on TPU; XLA owns buffers)")
define_flag("allocator_strategy", "auto_growth", "host allocator strategy name")
define_flag("tpu_matmul_precision", "default", "default|high|highest - lax precision for matmul/conv")
define_flag("tpu_eager_jit", True, "jit-cache eager primitive ops instead of op-by-op dispatch")
define_flag("lazy_eager", False,
            "lazy batching eager executor (ops/lazy.py): run_op defers ops "
            "into a per-thread segment and flushes them as ONE jitted "
            "executable at sync points (.numpy()/.item()/float()/bool()/"
            "print, tensor control flow, backward(), paddle.sync()) — "
            "O(1) dispatches per steady-state eager step instead of O(ops); "
            "off = the dispatch fast path pays one module-attribute check")
define_flag("lazy_max_segment_ops", 2048,
            "lazy eager: flush automatically once a segment accumulates "
            "this many deferred ops (bounds trace size and host memory for "
            "sync-free loops)")
define_flag("enable_unused_var_check", False, "unused-var detection parity flag")
define_flag("monitor", False,
            "enable the paddle_tpu.monitor stats registry + trace spans "
            "(platform/monitor.h STAT registry role); off = the dispatch "
            "fast path pays one module-attribute check and nothing else")
define_flag("lint", False,
            "run tpu-lint (paddle_tpu.analysis) over functions as they are "
            "traced by @to_static/TrainStep: trace-hazard warnings + "
            "lint.findings/lint.files monitor counters, once per function; "
            "off = one module-attribute check at trace time only")

# ---- resilience plane (paddle_tpu.faults + self-healing knobs) ----
define_flag("fault_inject", "",
            "deterministic fault-injection spec(s), ';'-separated "
            "site:kind[:p=..][:seed=..][:times=..][:after=..] strings "
            "(paddle_tpu.faults); empty = every injection site is one "
            "module-attribute check")
define_flag("ps_rpc_max_retries", 3,
            "PS client: transport-failure retries per RPC (exponential "
            "backoff + jitter; pushes stay exactly-once via per-client "
            "request sequencing)")
define_flag("ps_rpc_backoff_ms", 50.0,
            "PS client: initial retry backoff; doubles per attempt, "
            "capped at 2s, with up to 100% uniform jitter")
define_flag("ps_rpc_call_timeout_s", 120.0,
            "PS client: per-call deadline for connect + each response "
            "read (0 = wait forever)")
define_flag("ps_wal_dir", "",
            "PS durability: directory for the server's write-ahead delta "
            "log + crash-atomic snapshots; empty = in-memory only "
            "(PsServer(wal_dir=...) overrides per instance)")
define_flag("ps_wal_segment_mb", 16.0,
            "PS durability: WAL segment rollover size in MiB")
define_flag("ps_snapshot_every_records", 0,
            "PS durability: auto-compact the WAL into a snapshot every N "
            "committed delta records; 0 = manual snapshot() only")
define_flag("ps_replication_interval_ms", 20.0,
            "PS HA: standby poll interval for tailing the primary's delta "
            "stream (CMD_REPLICATE)")
define_flag("ps_communicator_max_requeues", 3,
            "Communicator: times one async push batch may be re-enqueued "
            "after a transport failure (client failover) before the "
            "worker records a permanent error")
define_flag("ps_ha_lease_ttl_s", 2.0,
            "PS HA: primary lease time-to-live in the rendezvous store; "
            "a standby promotes itself after this long without heartbeats")
define_flag("ps_ha_heartbeat_s", 0.5,
            "PS HA: lease heartbeat interval (must be well under "
            "FLAGS_ps_ha_lease_ttl_s)")
define_flag("online_max_staleness_s", 5.0,
            "online serving: a table whose last successful delta sync is "
            "older than this is considered stale; lookups then follow "
            "FLAGS_online_staleness_degrade")
define_flag("online_staleness_degrade", "serve_stale",
            "online serving: behavior past the staleness bound — "
            "'serve_stale' answers from the stale table (counted + one "
            "telemetry event per episode), 'reject' raises "
            "StalenessExceededError to the caller")
define_flag("online_delta_interval_ms", 50.0,
            "online serving: DeltaSubscriber poll interval for tailing "
            "the PS delta-push plane (CMD_DELTA)")
define_flag("online_delta_max_rows", 0,
            "online serving: cap on rows per delta pull (cut on version "
            "boundaries, never inside one); 0 = unbounded")
define_flag("bus_send_retries", 3,
            "fleet message bus: reconnect-and-resend attempts per frame "
            "before raising PeerGoneError")
define_flag("bus_send_backoff_ms", 50.0,
            "fleet message bus: initial reconnect backoff; doubles per "
            "attempt, capped at 2s")
define_flag("dataloader_max_worker_restarts", 2,
            "DataLoader: respawns allowed per worker slot before a dead "
            "worker becomes a hard error")

# ---- training guard plane (paddle_tpu.guard.GuardConfig.from_flags) ----
define_flag("guard_step_timeout_s", 0.0,
            "step watchdog: hard per-step deadline in seconds; 0 = "
            "auto-calibrate from the trailing median step duration after "
            "FLAGS_guard_warmup_steps completed steps")
define_flag("guard_warmup_steps", 5,
            "step watchdog: completed steps observed before the "
            "auto-calibrated deadline arms (compile steps excluded from "
            "nothing — the median absorbs them)")
define_flag("guard_timeout_factor", 10.0,
            "step watchdog: auto deadline = max(min, factor x trailing "
            "median step duration)")
define_flag("guard_min_timeout_s", 30.0,
            "step watchdog: floor for the auto-calibrated deadline")
define_flag("guard_loss_spike_ratio", 10.0,
            "divergence guard: a finite loss above ratio x trailing-median "
            "good loss counts as a bad step (rollback + skip); 0 disables "
            "the spike check (non-finite loss is always bad)")
define_flag("guard_snapshot_interval", 25,
            "divergence guard: steps between rolling in-memory last-good "
            "snapshots of params/slots/rng (rollback granularity)")
define_flag("guard_max_bad_steps", 3,
            "divergence guard: consecutive bad (rolled-back) steps before "
            "DivergedError is raised instead of skipping")
define_flag("guard_desync_interval", 0,
            "cross-rank desync detector: steps between parameter-"
            "fingerprint all-gathers across the data-parallel group; "
            "0 = disabled")
define_flag("guard_desync_timeout_s", 30.0,
            "cross-rank desync detector: how long to wait for peer "
            "fingerprints before giving up on a round")

# ---- serving plane (paddle_tpu.serving.EngineConfig.from_flags) ----
define_flag("serving_max_batch_size", 8,
            "dynamic batcher: max rows coalesced into one Predictor call")
define_flag("serving_batch_timeout_ms", 2.0,
            "dynamic batcher: max wait for co-batchable requests before "
            "dispatching a partial batch")
define_flag("serving_queue_depth", 256,
            "serving engine: pending-request cap; submits beyond it get "
            "explicit overload rejection (wire status 2), not queuing")
define_flag("serving_default_deadline_ms", 0.0,
            "serving engine: implicit per-request deadline (0 = none); "
            "expired requests are dropped before batching, wire status 3")
define_flag("serving_num_workers", 1,
            "serving engine: batcher worker threads (predictor dispatch "
            "itself is serialized; >1 overlaps host pre/post work)")
define_flag("serving_learn_buckets", True,
            "serving engine: a novel request signature registers a new "
            "shape bucket (one compile) instead of being rejected")
define_flag("serving_warmup", True,
            "serving engine: pre-run every declared bucket x batch size "
            "at start() so steady-state serving never compiles")

# ---- fleet serving tier (paddle_tpu.serving.fleet) --------------------------
define_flag("serving_client_max_retries", 3,
            "PredictorClient: bounded connect attempts per endpoint "
            "(exponential backoff + full jitter, mirrors the "
            "FLAGS_ps_rpc_* hardening) — a dead server burns milliseconds "
            "of the request deadline, not all of it")
define_flag("serving_client_backoff_ms", 25.0,
            "PredictorClient: initial reconnect backoff; doubles per "
            "attempt, capped at 1s, with full (0..100%) uniform jitter")
define_flag("serving_client_connect_timeout_s", 2.0,
            "PredictorClient: per-attempt TCP connect timeout (also "
            "clipped to the remaining per-call deadline)")
define_flag("fleet_heartbeat_s", 0.5,
            "fleet replica: heartbeat interval for the replica's "
            "ElasticManager lease (FleetRouter detects death at lease "
            "expiry OR on a dispatch connection error, whichever first)")
define_flag("fleet_lease_ttl_s", 2.0,
            "fleet replica: lease TTL; a replica whose lease is this "
            "stale is dead and its traffic re-routes")
define_flag("fleet_health_interval_s", 0.5,
            "fleet router: 'PDHQ' probe interval per replica (feeds the "
            "load-aware routing score: queue depth, SLO burn, "
            "warm_start_ms) and the rejoin detector for recovered "
            "replicas")
define_flag("fleet_max_replicas", 16,
            "fleet router: replica-id space scanned in the rendezvous "
            "store for registrations")
define_flag("fleet_failover_attempts", 3,
            "fleet router: distinct replicas tried per request before "
            "giving up (each retry bounded by the request's ORIGINAL "
            "deadline; the sequence ledger keeps delivery exactly-once)")
define_flag("fleet_route_burn_weight", 2.0,
            "fleet router: weight of a replica's shortest-window SLO "
            "burn rate in its routing score (score = queue fraction + "
            "weight * burn; lowest score wins)")
define_flag("fleet_canary_burn", 1.0,
            "fleet rollout: canary burn-rate threshold — a pushed model "
            "version whose canary-replica tenant burn exceeds this rolls "
            "back instantly via the guard checkpoint .bak generation")
define_flag("fleet_hbm_budget_mb", 0.0,
            "fleet replica: HBM budget for hosted model weights "
            "(mem.model.<name>.bytes admission control: a push that "
            "would exceed it evicts idle LRU tenants first, then is "
            "rejected; 0 = unlimited)")

# ---- hot-path overlap plane (io/prefetch.py, parallel/reducer.py, fused opt) --
define_flag("prefetch", False,
            "async double-buffered host->device prefetch: hapi.Model.fit "
            "feeds the train step through io.prefetch.DevicePrefetcher (a "
            "feeder thread runs jax.device_put FLAGS_prefetch_depth batches "
            "ahead, hiding h2d + host batch assembly under the previous "
            "step); off = one module-attribute check per epoch (maybe_wrap)")
define_flag("prefetch_depth", 2,
            "prefetch: batches the feeder thread stages on device ahead of "
            "the consumer (the reference buffered_reader double-buffer "
            "depth); also the drop bound on preemption — at most this many "
            "staged batches are discarded, the resume cursor only counts "
            "CONSUMED batches")
define_flag("dp_bucket_mb", 25,
            "bucketed gradient reduction (parallel/reducer.py): gradient "
            "bytes coalesced per collective in the backward-interleaved "
            "DP reduction (reference DataParallel comm_buffer_size=25MB); "
            "smaller = earlier overlap, larger = fewer collectives")
define_flag("amp_fused_update", True,
            "GradScaler.step folds unscale + found_inf check + gate into "
            "the optimizer's fused update executable (one dispatch, no "
            "pre-dispatch host sync on found_inf); off = the legacy "
            "unscale_-then-step path with its per-step host sync")

# ---- observability plane (paddle_tpu.obs: step timeline + flight recorder) --
define_flag("obs_timeline", False,
            "record a per-step phase timeline (data_wait/h2d/trace_compile/"
            "device_compute/collective/optimizer/snapshot ...) into a "
            "bounded ring (paddle_tpu.obs.StepTimeline); adds a "
            "block_until_ready fence per step so device compute is "
            "attributed honestly; off = one module-attribute check per "
            "instrumented site")
define_flag("obs_flight_recorder", False,
            "keep the black-box flight recorder armed: last-N step "
            "records + monitor-counter deltas + recent collectives + "
            "guard/fault events, dumped to one JSON artifact on guard "
            "errors, serving overload, SIGTERM preemption, or dump(); "
            "off = one module-attribute check per instrumented site")
define_flag("obs_ring_steps", 64,
            "obs: step records kept in the timeline/flight-recorder ring")
define_flag("obs_ring_snapshots", 16,
            "obs: per-step monitor-counter deltas kept in the flight "
            "recorder ring")
define_flag("obs_dump_dir", "flight_recorder",
            "obs: directory flight-recorder dumps are written to when no "
            "explicit path is given")
define_flag("obs_dump_min_interval_s", 30.0,
            "obs: min seconds between AUTOMATIC dumps for the same reason "
            "(overload storms must not flood the disk); explicit "
            "dump(path=...) calls are never rate-limited")

# ---- memory attribution plane (paddle_tpu.obs.memory) ----------------------
define_flag("mem_census", False,
            "HBM memory attribution (obs/memory.py): tag device buffers at "
            "their creation seams (params/slots/activations/prefetch "
            "staging/serving buckets/lazy segments) and let census() bucket "
            "live bytes per tag per device, publishing mem.<tag>.bytes "
            "gauges; off = every tag seam pays one module-attribute check")
define_flag("mem_census_ring", 16,
            "mem census: snapshots kept in the census ring (the flight "
            "recorder embeds this ring in its dump)")
define_flag("mem_top_k", 8,
            "mem census: top-K largest live buffers (with tag + origin) "
            "reported by top_buffers() and the OOM forensics dump")
define_flag("mem_leak_window", 8,
            "mem leak watch: a tag whose census bytes grow strictly for "
            "this many consecutive censuses is flagged as a leak suspect "
            "(warning + mem.leak_suspects counter); 0 disables the check")
# ---- request tracing + SLO plane (obs/trace.py + obs/slo.py) ---------------
define_flag("trace", False,
            "request-scoped distributed tracing (obs/trace.py): mint a "
            "trace context per PredictorClient request, carry it over the "
            "wire in an optional 'PDTC' frame and through the fleet message "
            "bus, and record spans (client.send/serving.request/queue_wait/"
            "batch/dispatch/reply, ps.rpc.*) into a tail-sampled ring that "
            "joins the flight-recorder dump and chrome-trace export; "
            "off = every span site pays one module-attribute check")
define_flag("trace_ring", 64,
            "tracing: finished traces kept per ring (one ring for healthy "
            "traces, one PROTECTED ring for over-deadline/rejected/errored/"
            "SLO-violating traces that tail sampling always keeps)")
define_flag("slo_latency_ms", 0.0,
            "SLO plane (obs/slo.py): latency objective for serving e2e "
            "latency — a request slower than this (or rejected/deadline-"
            "expired/errored) burns error budget; 0 = SLO plane off "
            "(one attribute check per recorded request)")
define_flag("slo_target", 0.999,
            "SLO plane: availability target (fraction of requests that "
            "must meet the latency objective); burn rate = bad_fraction / "
            "(1 - target), so burn 1.0 = exactly consuming the budget")
define_flag("slo_windows", "60,300,3600",
            "SLO plane: comma-separated burn-rate window lengths in "
            "seconds (multi-window burn alerting: short window catches "
            "fast burn, long window catches slow leaks)")
define_flag("slo_shed_burn", 0.0,
            "SLO plane: admission hook threshold — when the SHORTEST "
            "window's burn rate exceeds this, ServingEngine.submit sheds "
            "new requests as overloaded before the budget burns; "
            "0 = never shed on burn")

# ---- fleet telemetry plane (obs/telemetry.py) -----------------------------
define_flag("telemetry", False,
            "fleet telemetry plane (obs/telemetry.py): processes run a "
            "TelemetryExporter pushing delta-compressed counters, "
            "mergeable DDSketch histograms, and immediate events to the "
            "TelemetryCollector found via TCPStore rendezvous; off = "
            "zero telemetry threads/sockets")
define_flag("telemetry_interval_s", 0.25,
            "telemetry: exporter metric-push period in seconds (events "
            "push immediately regardless)")
define_flag("telemetry_buffer", 256,
            "telemetry: exporter's bounded drop-oldest event buffer — a "
            "dead collector costs at most this many queued events "
            "(telemetry.dropped counts the overflow), never serving "
            "throughput")
define_flag("telemetry_ring", 256,
            "telemetry: collector's per-(source, metric) time-series "
            "ring length and its fleet event-ring length")
define_flag("telemetry_death_after_s", 1.5,
            "telemetry: collector declares a silent source dead after "
            "this many seconds without a push (socket EOF on SIGKILL is "
            "the fast path; this reaper catches wedged-not-dead)")
define_flag("telemetry_incident_min_interval_s", 30.0,
            "telemetry: minimum spacing between correlated-incident "
            "fan-outs — a crash loop yields one fleet-wide dump set per "
            "window, not a dump storm")

# ---- unified RPC substrate (utils/net.py) ---------------------------------
define_flag("net_auth_token", "",
            "RPC substrate: shared secret enabling per-frame HMAC auth "
            "on EVERY plane at once (serving, PS, bus, telemetry) — "
            "clients open each connection with a 'PDAH' challenge "
            "handshake and both sides speak 'PDAR' HMAC-SHA256 records; "
            "unauthenticated peers are rejected and counted "
            "(net.auth_rejects). Empty = off: the wire stays "
            "byte-identical to the pre-substrate protocols")
define_flag("net_tls_cert", "",
            "RPC substrate: path to a PEM cert chain — set together "
            "with net_tls_key to wrap every plane's listener in TLS "
            "(clients also present it for mutual TLS); empty = off")
define_flag("net_tls_key", "",
            "RPC substrate: path to the PEM private key for "
            "net_tls_cert (empty = key lives in the cert file)")
define_flag("net_tls_ca", "",
            "RPC substrate: path to the PEM CA bundle peers are "
            "verified against — on clients it turns on server "
            "verification, on servers it requires client certs")
define_flag("net_deadline_wire", False,
            "RPC substrate: prefix every request with a 'PDDL' "
            "absolute-deadline frame so servers DROP expired work "
            "(net.deadline_drops) instead of computing it. Off by "
            "default: pre-substrate peers reject the unknown magic, so "
            "flip it only on same-version deployments")

# ---- SLO-driven autoscaler (serving/autoscaler.py) ------------------------
define_flag("autoscaler_interval_s", 0.5,
            "autoscaler: control-loop tick period — each tick senses the "
            "collector's fleet signal (worst shortest-window burn + queue "
            "fraction), asks the policy for a decision, and actuates it")
define_flag("autoscaler_burn_high", 1.0,
            "autoscaler policy: scale OUT when the worst replica's "
            "shortest-window SLO burn exceeds this (1.0 = consuming the "
            "error budget exactly as provisioned)")
define_flag("autoscaler_burn_low", 0.25,
            "autoscaler policy: burn must be at or below this for the "
            "idle clock to run (scale-in hysteresis band: the gap to "
            "autoscaler_burn_high is where nothing happens)")
define_flag("autoscaler_queue_high", 0.8,
            "autoscaler policy: scale OUT when the fleet queue fraction "
            "(queued work / aggregate queue capacity) exceeds this")
define_flag("autoscaler_queue_low", 0.2,
            "autoscaler policy: queue fraction must be at or below this "
            "for the idle clock to run (scale-in hysteresis band)")
define_flag("autoscaler_cooldown_s", 5.0,
            "autoscaler policy: minimum spacing between scale actions in "
            "the SAME direction — flapping traffic cannot thrash the "
            "pool faster than one step per cooldown")
define_flag("autoscaler_idle_after_s", 10.0,
            "autoscaler policy: the fleet must stay calm (burn and queue "
            "below the low thresholds) this long before ONE replica is "
            "drained; the clock restarts after each scale-in")
define_flag("autoscaler_zero_after_s", 60.0,
            "autoscaler policy: with autoscaler_min_replicas=0, a fleet "
            "calm this long scales TO ZERO (drains every replica); idle "
            "tenants are evicted at the same threshold under the "
            "FLAGS_fleet_hbm_budget_mb LRU when autoscaler_tenant_idle_s "
            "is unset")
define_flag("autoscaler_min_replicas", 1,
            "autoscaler policy: floor of the replica pool (0 allows "
            "scale-to-zero)")
define_flag("autoscaler_max_replicas", 0,
            "autoscaler policy: ceiling of the replica pool; 0 = use "
            "FLAGS_fleet_max_replicas")
define_flag("autoscaler_step", 1,
            "autoscaler policy: replicas added per scale-out decision "
            "(scale-in always drains one at a time)")
define_flag("autoscaler_spawn_timeout_s", 15.0,
            "autoscaler pool: a spawned replica must answer its first "
            "'PDHQ' probe within this window or it is reaped (record + "
            "lease reclaimed, autoscaler.spawn_failures counted)")
define_flag("autoscaler_spawn_retries", 3,
            "autoscaler pool: consecutive spawn failures tolerated "
            "before scale-out is declared blocked (the collector's "
            "scale_blocked alert fires); one success resets the budget")
define_flag("autoscaler_tenant_idle_s", 0.0,
            "autoscaler: evict a hosted ModelTenant idle this long with "
            "an empty queue (scale-to-zero for tenants, via the "
            "replica's HBM-budget LRU eviction path); 0 = fall back to "
            "autoscaler_zero_after_s, negative = never evict tenants")
define_flag("autoscaler_ledger_ring", 128,
            "autoscaler: decision-ledger ring length (every scale action "
            "with its triggering evidence; dumped into the flight "
            "recorder and rendered by `monitor top`)")

# ---- executable plane (core/executable.py + core/compile_cache.py) --------
define_flag("compile_cache_dir", "",
            "persistent on-disk executable cache (core/compile_cache.py): "
            "novel programs built through the Executable substrate are "
            "AOT-serialized (jax.export) under a key of (canonical StableHLO "
            "hash, topology fingerprint, jax version, relevant flags); a "
            "second process running the same workload deserializes instead "
            "of compiling (fleet warm start). Empty = off: every build site "
            "pays one module-attribute check")
define_flag("compile_cache_mb", 1024,
            "compile cache: on-disk size cap in MB; least-recently-used "
            "entries beyond it are evicted at store/gc time "
            "(compile_cache.evictions counter)")
# ---- LLM continuous-batching serving (serving/llm.py) ---------------------
define_flag("llm_num_slots", 8,
            "LLM engine: KV-cache pool slots = max sequences decoding "
            "concurrently; one fixed-shape decode executable covers all "
            "slots, so this is also the decode batch width")
define_flag("llm_max_len", 256,
            "LLM engine: per-slot KV page length (prompt + generated "
            "ceiling); pool bytes scale linearly with it "
            "(see README 'LLM serving' sizing recipe)")
define_flag("llm_prefill_buckets", "",
            "LLM engine: comma-separated prefill length buckets (prompts "
            "pad up to the next bucket; one cached prefill executable per "
            "bucket). Empty = powers of two from 8 up to llm_max_len")
define_flag("llm_max_new_tokens", 64,
            "LLM engine: default generation budget per request when the "
            "submit call doesn't set one")
define_flag("llm_queue_depth", 256,
            "LLM engine: max queued (not yet admitted) requests before "
            "submit sheds with ServerOverloadedError")
define_flag("llm_default_deadline_ms", 0.0,
            "LLM engine: deadline applied to requests that don't carry "
            "one; sequences past it are evicted at the next decode step "
            "(llm.evictions.deadline). 0 = no default")
define_flag("llm_warmup", True,
            "LLM engine: trace+compile every prefill bucket and the "
            "decode step at start() so steady-state serving performs "
            "zero compiles (the jit.* retrace counters stay flat)")
define_flag("llm_quant", "off",
            "LLM engine decode quantization arm: 'int8' applies "
            "quant_weight_only to the decoder matmuls (Linear + "
            "ColumnParallelLinear/RowParallelLinear) at engine init; "
            "'off' serves fp32 weights")
define_flag("llm_kv_int8", False,
            "LLM engine: store KV-cache pages as int8 with one "
            "dequantization scale per slot (computed at prefill, "
            "clipped into at decode) — 4x pool bytes reduction")

define_flag("lazy_cache_entries", 256,
            "lazy eager: max cached segment replay executables "
            "(the ops/lazy.py executable ledger); least-recently-used entries are "
            "evicted beyond the cap (lazy.cache_evictions counter) instead "
            "of the cache growing without bound under shape churn")

# ---- concurrency sanitizer (utils/syncwatch.py) ---------------------------
define_flag("sync_watch", False,
            "concurrency sanitizer (utils/syncwatch.py): syncwatch.lock()/"
            "rlock() factories hand out watched wrappers that record "
            "per-thread held-sets + acquisition stacks, maintain the "
            "observed lock-order graph, and raise SyncOrderError (naming "
            "BOTH acquisition stacks) on a cycle BEFORE the acquire would "
            "wedge; off = the factories return plain threading locks "
            "(one module-attribute check at lock-construction time, zero "
            "per-acquire cost)")
define_flag("sync_hold_warn_ms", 0.0,
            "syncwatch: warn with the acquisition stack when a watched "
            "lock was held longer than this many ms (observed on release "
            "into the sync.lock_hold_ms histogram; the live thread table "
            "`python -m paddle_tpu.monitor threads` flags still-held "
            "locks over the threshold); 0 = record the histogram only")
define_flag("sync_order_fatal", True,
            "syncwatch: raise SyncOrderError on a lock-order cycle "
            "(False: warn + count sync.order_violations and continue — "
            "for soaks that want the census without dying on first hit)")
