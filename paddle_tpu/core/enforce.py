"""Typed error system + enforce helpers.

Reference parity: `paddle/fluid/platform/enforce.h:1` (PADDLE_ENFORCE_*
macros) and `platform::errors::*` typed errors, surfaced to python as the
matching builtin exception types (the reference's pybind error translation
maps InvalidArgument->ValueError, NotFound->..., etc.), so user code that
catches builtins keeps working while `type(e).__name__` carries the typed
classification and the message carries the [Hint] block.
"""
from __future__ import annotations

from typing import Any, Optional


def _fmt(summary: str, hint: Optional[str]) -> str:
    msg = summary
    if hint:
        msg += f"\n  [Hint] {hint}"
    return msg


class InvalidArgumentError(ValueError):
    pass


class NotFoundError(FileNotFoundError):
    pass


class OutOfRangeError(IndexError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ResourceExhaustedError(MemoryError):
    pass


class PreconditionNotMetError(RuntimeError):
    pass


class PermissionDeniedError(PermissionError):
    pass


class ExecutionTimeoutError(TimeoutError):
    pass


class UnimplementedError(NotImplementedError):
    pass


class UnavailableError(RuntimeError):
    pass


class FatalError(RuntimeError):
    pass


class ExternalError(RuntimeError):
    pass


# errors namespace (platform::errors::InvalidArgument(...) style factories)
class errors:
    InvalidArgument = InvalidArgumentError
    NotFound = NotFoundError
    OutOfRange = OutOfRangeError
    AlreadyExists = AlreadyExistsError
    ResourceExhausted = ResourceExhaustedError
    PreconditionNotMet = PreconditionNotMetError
    PermissionDenied = PermissionDeniedError
    ExecutionTimeout = ExecutionTimeoutError
    Unimplemented = UnimplementedError
    Unavailable = UnavailableError
    Fatal = FatalError
    External = ExternalError


def enforce(cond: Any, err: Exception | str, hint: str = ""):
    """PADDLE_ENFORCE: raise when cond is falsy."""
    if not cond:
        if isinstance(err, str):
            err = PreconditionNotMetError(_fmt(err, hint))
        raise err
    return cond


def enforce_not_none(val, what: str = "value", hint: str = ""):
    if val is None:
        raise NotFoundError(_fmt(f"{what} should not be None.", hint))
    return val


def _cmp(a, b, op, opname, hint):
    ok = op(a, b)
    if not ok:
        raise InvalidArgumentError(_fmt(
            f"Expected {a!r} {opname} {b!r}, but received "
            f"{a!r}:{type(a).__name__} vs {b!r}:{type(b).__name__}.", hint))


def enforce_eq(a, b, hint: str = ""):
    _cmp(a, b, lambda x, y: x == y, "==", hint)


def enforce_ne(a, b, hint: str = ""):
    _cmp(a, b, lambda x, y: x != y, "!=", hint)


def enforce_gt(a, b, hint: str = ""):
    _cmp(a, b, lambda x, y: x > y, ">", hint)


def enforce_ge(a, b, hint: str = ""):
    _cmp(a, b, lambda x, y: x >= y, ">=", hint)


def enforce_lt(a, b, hint: str = ""):
    _cmp(a, b, lambda x, y: x < y, "<", hint)


def enforce_le(a, b, hint: str = ""):
    _cmp(a, b, lambda x, y: x <= y, "<=", hint)
