"""Data types for paddle_tpu.

TPU-first notes: bfloat16 is the preferred low-precision dtype (MXU native);
float64 is discouraged on TPU (emulated) but supported for CPU oracle tests.

Reference parity: mirrors the dtype surface of PaddlePaddle's
`phi/common/data_type.h` and `python/paddle/fluid/core` VarDesc dtypes.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes  # ships with jax

# Canonical dtype objects are numpy dtypes (jax uses them natively).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float": float32,
    "double": float64,
    "half": float16,
    "int": int32,
    "long": int64,
}

_DEFAULT_DTYPE = [np.dtype("float32")]


def convert_dtype(dtype):
    """Normalise any dtype spec (str, np.dtype, jnp dtype, paddle-style) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "").replace("fp", "float")
        if key in _STR2DTYPE:
            return np.dtype(_STR2DTYPE[key])
        return np.dtype(key)
    return np.dtype(dtype)


def set_default_dtype(dtype):
    """paddle.set_default_dtype parity (fluid/framework.py)."""
    d = convert_dtype(dtype)
    if d not in (np.dtype("float16"), np.dtype(ml_dtypes.bfloat16), np.dtype("float32"), np.dtype("float64")):
        raise TypeError(f"set_default_dtype only supports floating dtypes, got {dtype}")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def is_floating(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.integer) or d == np.dtype("bool")


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
