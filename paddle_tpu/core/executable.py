"""One executable substrate under all four dispatch regimes.

Reference parity: the reference extracted phi out of fluid so eager and
static-graph execution share ONE kernel library instead of two
(PAPER.md §1 rows 3/6) — the same move one level up. Before this module,
`jit/train_step.py` (TrainStep/SPMDTrainStep), `jit/to_static.py`,
`ops/lazy.py` segments, and `serving/engine.py` bucket warm-up each grew
a private copy of the build→cache→dispatch plumbing: a signature cache
(`_seen_sigs` / `_prog_sig` / `_SEG_CACHE` / `_dispatched_sigs`), retrace
accounting, donation policy, timeline booking, and the OOM-dump seam —
so every cross-cutting feature (the PR-10 memory census, and now the
persistent compile cache) paid a ×4 implementation tax. The substrate
here is what each regime parameterizes instead:

- `ExecutableLedger` — the signature cache + retrace accounting + LRU
  executable cache, one implementation. `note(sig)` answers "novel?" and
  books the retrace counters under the regime's kind string (counter
  names unchanged: `jit.<kind>.traces` / `.retraces`).
- `booking(kind)` — the timeline phase around a dispatch. Opens
  `device_compute`; if the regime reports `bk.compiled()` the phase is
  renamed to `trace_compile` in place (the `_Phase.name` late-rename
  trick), so a compile is attributed exactly where it happened. A
  booking that finds the calling thread ALREADY inside an open phase
  suppresses its own phase entirely — this closes the latent
  double-accounting seam where a lazy-segment flush nested inside a
  step's phase booked the same wall seconds twice and broke the
  phase-sum≈wall invariant. Monitor counters (`trace_compile`,
  `trace_compile.<kind>`) are still counted when nested — suppression is
  about wall-time attribution, not compile counting.
- `acquire(kind, jitted, args)` — the persistent-cache build step
  (core/compile_cache.py): key the canonical StableHLO, deserialize a
  prior process's AOT-serialized executable on hit (re-wrapped with the
  regime's declared donation), export+persist on miss. Cache off = one
  module-attribute check, zero overhead.
- `dispatch_guard(label, report)` — the OOM forensics seam: the
  `mem.alloc` fault drill site plus `obs.memory.maybe_dump_oom` on the
  way out of a failed dispatch.

The post-commit re-tag half of the lifecycle stays with the regime (only
it knows which arrays are params vs slots vs pool); the substrate's
`retag` hook exists so regimes declare it once at construction.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Iterable, List, Optional, Tuple

from .. import faults as _faults
from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from . import compile_cache as _cc

__all__ = ["ExecutableLedger", "booking", "acquire", "dispatch_guard"]


class ExecutableLedger:
    """Signature ledger + optional LRU executable cache for one dispatch
    regime. Replaces TrainStep `_seen_sigs`, to_static `_seen_sigs` +
    `_prog_sig`, lazy `_SEG_CACHE`/`_SEG_SEEN`, and serving
    `_dispatched_sigs` with one thread-safe implementation.

    `note(sig)` is the novelty test + retrace bookkeeping; `get`/`put`
    manage cached callables (LRU when `cap` is set, `evictions` counted,
    `on_evict(sig, value)` fired outside nothing — callers use it to
    mirror eviction counters)."""

    def __init__(self, kind: str, cap: Optional[int] = None,
                 on_evict: Optional[Callable[[Any, Any], None]] = None):
        self.kind = kind
        self._lock = threading.RLock()
        self._seen: set = set()
        self._cache: "OrderedDict[Any, Any]" = OrderedDict()
        self._cap = cap
        self.on_evict = on_evict
        self.evictions = 0
        # the signature the regime's published program was built for
        # (to_static's old `_prog_sig` role)
        self.current_sig: Any = None

    # ---- novelty / retrace accounting ----
    def note(self, sig, detail=None, retrace: bool = True) -> bool:
        """Record `sig` as dispatched; True when it was novel (the call
        ahead pays trace+compile). Books monitor retrace counters under
        this ledger's kind — `detail` overrides the signature logged
        (lazy passes op-count + leaf signature)."""
        with self._lock:
            novel = sig not in self._seen
            first = not self._seen
            if novel:
                self._seen.add(sig)
        if novel and retrace and _monitor._ENABLED:
            _monitor.record_retrace(self.kind,
                                    sig if detail is None else detail,
                                    first=first)
        return novel

    def seen(self, sig) -> bool:
        with self._lock:
            return sig in self._seen

    def seen_sigs(self) -> set:
        with self._lock:
            return set(self._seen)

    # ---- cached callables (LRU) ----
    def get(self, sig):
        with self._lock:
            if sig not in self._cache:
                return None
            self._cache.move_to_end(sig)
            return self._cache[sig]

    def put(self, sig, value) -> None:
        evicted: List[Tuple[Any, Any]] = []
        with self._lock:
            self._cache[sig] = value
            self._cache.move_to_end(sig)
            if self._cap is not None:
                while len(self._cache) > max(1, int(self._cap)):
                    evicted.append(self._cache.popitem(last=False))
                    self.evictions += 1
        for esig, evalue in evicted:
            if self.on_evict is not None:
                self.on_evict(esig, evalue)

    def set_cap(self, cap: Optional[int]) -> None:
        with self._lock:
            self._cap = cap
        if cap is not None:
            # shrink immediately (watch_flag lowering the cap mid-run)
            self.put_noop()

    def put_noop(self) -> None:
        """Re-run the eviction sweep without inserting (cap shrink)."""
        evicted: List[Tuple[Any, Any]] = []
        with self._lock:
            if self._cap is not None:
                while len(self._cache) > max(1, int(self._cap)):
                    evicted.append(self._cache.popitem(last=False))
                    self.evictions += 1
        for esig, evalue in evicted:
            if self.on_evict is not None:
                self.on_evict(esig, evalue)

    def keys(self) -> list:
        with self._lock:
            return list(self._cache.keys())

    def items(self) -> list:
        with self._lock:
            return list(self._cache.items())

    def clear(self, seen: bool = True) -> None:
        with self._lock:
            self._cache.clear()
            if seen:
                self._seen.clear()
            self.current_sig = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)

    def __contains__(self, sig) -> bool:
        with self._lock:
            return sig in self._cache


class _Booking:
    """Timeline booking around one dispatch. Opens `device_compute`,
    renamed in place to `trace_compile` if the regime calls
    `compiled()`. Nested inside an already-open phase on this thread →
    no phase of its own (the enclosing phase owns the wall time; monitor
    compile counters still fire)."""

    __slots__ = ("kind", "did_compile", "_ctx")

    def __init__(self, kind: str):
        self.kind = kind
        self.did_compile = False
        self._ctx = None

    def __enter__(self):
        if _obs._TL_ENABLED and not _obs.in_phase():
            self._ctx = _obs.timeline().phase("device_compute")
            self._ctx.__enter__()
        return self

    def compiled(self) -> None:
        """The dispatch underway traced+compiled a novel program: rename
        the open phase and count it. This is THE compile counter — the
        zero-compile warm-start acceptance reads `trace_compile`."""
        if self.did_compile:
            return
        self.did_compile = True
        if self._ctx is not None:
            self._ctx.name = "trace_compile"
        if _monitor._ENABLED:
            _monitor.count("trace_compile")
            _monitor.count(f"trace_compile.{self.kind}")

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
            self._ctx = None
        return False


def booking(kind: str) -> _Booking:
    return _Booking(kind)


class _DispatchGuard:
    """OOM forensics around one dispatch: the `mem.alloc` fault drill
    site on the way in, `maybe_dump_oom` (RESOURCE_EXHAUSTED dump) on
    the way out of a failure. `report` is a zero-arg lambda producing
    the executable memory breakdown — only called when dumping."""

    __slots__ = ("label", "report")

    def __init__(self, label: str, report: Optional[Callable] = None):
        self.label = label
        self.report = report

    def __enter__(self):
        if _faults._ENABLED:
            try:
                _faults.check("mem.alloc")
            except Exception as exc:
                # an __enter__ raise skips __exit__ — dump here so the
                # injected fault exercises the same forensics path
                _mem.maybe_dump_oom(exc, executable=self.label,
                                    report=self.report)
                raise
        return self

    def __exit__(self, etype, exc, tb):
        if exc is not None:
            _mem.maybe_dump_oom(exc, executable=self.label,
                                report=self.report)
        return False


def dispatch_guard(label: str, report: Optional[Callable] = None):
    return _DispatchGuard(label, report)


# ---- persistent-cache build step -------------------------------------------

def acquire(kind: str, jitted, args: Iterable[Any], donate: Tuple[int, ...] = (),
            label: str = "", mesh_shape=None):
    """Build step for a novel signature. With the persistent cache off
    (default) this is `(jitted, "fresh")` after one module-attribute
    check. With `FLAGS_compile_cache_dir` set: lower to StableHLO, key
    it, and either deserialize a prior process's serialized executable
    (source `"disk"` — the call is re-wrapped in `jax.jit` with the
    regime's declared `donate` argnums, preserving the `is_deleted()`
    donation guarantees) or export+persist this process's build for the
    next one (source `"fresh"`). Every failure path degrades to the
    fresh jitted callable — the cache can only ever save work.

    NOTE: programs whose avals the export path cannot serialize (typed
    PRNG keys, closures over opaque out-trees) count `export_skips`;
    regimes that want cache coverage pass raw-key-data adapter programs
    when `compile_cache.enabled()` (see TrainStep._build)."""
    if not _cc._DIR:
        return jitted, "fresh"
    import jax
    args = tuple(args)
    try:
        text = jitted.lower(*args).as_text()
        key = _cc.cache_key(text, mesh_shape=mesh_shape, extra=(kind,))
    except Exception as e:
        _cc.note_export_skip(f"lower: {type(e).__name__}: {e}")
        return jitted, "fresh"
    blob = _cc.lookup(key, mesh_shape=mesh_shape)
    if blob is not None:
        try:
            exp = jax.export.deserialize(blob)
            call = jax.jit(lambda *a: exp.call(*a),
                           donate_argnums=tuple(donate))
            if _monitor._ENABLED:
                _monitor.log_event("compile_cache.hit", kind=kind, key=key,
                                   label=label)
            return call, "disk"
        except Exception:
            _cc._fallback(key, "deserialize_failed")
    _cc.note_miss()
    try:
        exp = jax.export.export(jitted)(*args)
        _cc.store(key, exp.serialize(), kind=kind, label=label,
                  mesh_shape=mesh_shape)
    except Exception as e:
        _cc.note_export_skip(f"export: {type(e).__name__}: {e}")
    return jitted, "fresh"
