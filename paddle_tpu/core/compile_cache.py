"""Persistent on-disk executable cache — fleet warm start in seconds.

Reference parity: the reference keeps a program/executor cache so one
process never recompiles a ProgramDesc it already built
(`framework/executor_cache.h` role); at fleet scale the same waste happens
ACROSS processes — every serving replica re-warms its bucket ladder and
every preempted trainer re-traces its step, recompiling programs some
other process already compiled. This module is the cross-process half of
the `core/executable.py` substrate: novel builds are AOT-serialized via
the `jax.export` path `jit/save_load.py` already rides, keyed by

    sha256(canonical StableHLO text
           + topology fingerprint (device kind, device count, mesh shape)
           + jax version
           + relevant flags)

and persisted crash-atomically (`framework/sharded_io.atomic_write`, CRC
manifests, tmp+rename with per-writer tmp names so lock-free concurrent
writers are last-writer-wins). A second process with the same program and
topology deserializes instead of compiling; corrupt, stale-version, or
wrong-topology entries fall back to a fresh compile (`fallbacks` counter,
never an error). The disk footprint is a size-capped LRU
(`FLAGS_compile_cache_mb`), age-ranked by each entry's last-use stamp.

Hot-path contract (monitor/faults/obs regime): every build site checks
ONE module attribute (`_DIR`) and pays nothing else while the flag is
unset. Counters are plain module ints (`stats()`), mirrored to
`paddle_tpu.monitor` counters `compile_cache.*` when the monitor is on.

Fault drill site: `compile_cache.write` (torn/corrupt blob bytes — the
manifest CRC is of the INTENDED bytes, so a mangled write fails lookup
verification and falls back).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .. import faults as _faults
from .. import monitor as _monitor
from . import flags as _flags

__all__ = [
    "enabled", "cache_dir", "cache_key", "topology_fingerprint",
    "lookup", "store", "entries", "gc", "verify", "stats", "reset_stats",
    "warm_start_report",
]

_SCHEMA = 1

# ---- gate (one module attribute on the disabled path) ----------------------
_DIR: str = str(_flags.flag("compile_cache_dir") or "")

# process-lifetime counters (monitor may be off; serving stats() and tests
# read these regardless)
hits: int = 0
misses: int = 0
fallbacks: int = 0
stores: int = 0
evictions: int = 0
export_skips: int = 0   # programs the export path cannot serialize


def _on_dir(value) -> None:
    global _DIR
    _DIR = str(value or "")
    _wire_native_cache(_DIR)


def _wire_native_cache(dirname: str) -> None:
    """Best-effort: also point jax's own persistent compilation cache at
    the same directory so the StableHLO→binary stage is cross-process
    cached too (on TPU that is the dominant cost; the export blob alone
    removes the trace). Clearing the flag UNWIRES it — a stale cache dir
    must not keep adding write traffic to every later compile."""
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(dirname, "xla") if dirname else None)
    except Exception:
        pass


_flags.watch_flag("compile_cache_dir", _on_dir)
if _DIR:
    _wire_native_cache(_DIR)


def enabled() -> bool:
    return bool(_DIR)


def cache_dir() -> str:
    return _DIR


def _count(name: str, delta: int = 1) -> None:
    if _monitor._ENABLED:
        _monitor.count(f"compile_cache.{name}", delta)


# ---- key anatomy -----------------------------------------------------------

def topology_fingerprint(mesh_shape=None) -> str:
    """Device kind × count (+ mesh axes) — an entry compiled for one
    topology must never be offered to another."""
    import jax
    devs = jax.devices()
    fp = f"{devs[0].device_kind}x{len(devs)}"
    if mesh_shape:
        fp += ";mesh=" + ",".join(f"{a}={n}" for a, n in
                                  (mesh_shape.items()
                                   if isinstance(mesh_shape, dict)
                                   else mesh_shape))
    return fp


def _canonicalize(text: str) -> str:
    """Strip location metadata and trailing whitespace so cosmetically
    different lowerings of the same program hash identically."""
    lines = []
    for ln in text.splitlines():
        if ln.lstrip().startswith("loc("):
            continue
        lines.append(ln.rstrip())
    return "\n".join(lines)


def _relevant_flags() -> str:
    vals = []
    for name in ("tpu_matmul_precision", "check_nan_inf"):
        vals.append(f"{name}={_flags.flag(name)}")
    return ";".join(vals)


def cache_key(stablehlo_text: str, mesh_shape=None,
              extra: Tuple[str, ...] = ()) -> str:
    import jax
    h = hashlib.sha256()
    h.update(_canonicalize(stablehlo_text).encode())
    h.update(b"\x00" + topology_fingerprint(mesh_shape).encode())
    h.update(b"\x00" + jax.__version__.encode())
    h.update(b"\x00" + _relevant_flags().encode())
    for item in extra:
        h.update(b"\x00" + str(item).encode())
    return h.hexdigest()[:40]


# ---- storage layout: <dir>/<key>.bin + <dir>/<key>.json --------------------

def _paths(key: str, dirname: Optional[str] = None) -> Tuple[str, str]:
    d = dirname or _DIR
    return os.path.join(d, key + ".bin"), os.path.join(d, key + ".json")


def _read_manifest(mpath: str) -> Optional[dict]:
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_manifest(mpath: str, manifest: dict) -> None:
    from ..framework.sharded_io import atomic_write
    atomic_write(mpath, json.dumps(manifest).encode(), unique_tmp=True)


def _prune(key: str, dirname: Optional[str] = None) -> None:
    for path in _paths(key, dirname):
        try:
            os.remove(path)
        except OSError:
            pass


def _fallback(key: str, why: str, dirname: Optional[str] = None,
              prune: bool = True) -> None:
    global fallbacks
    fallbacks += 1
    _count("fallbacks")
    if _monitor._ENABLED:
        _monitor.log_event("compile_cache.fallback", key=key, why=why)
    if prune:
        _prune(key, dirname)


def lookup(key: str, mesh_shape=None) -> Optional[bytes]:
    """Serialized executable bytes for `key`, or None (miss OR fallback).
    Verifies the manifest CRC and re-validates the recorded jax version /
    topology against the current process (defense in depth — they are in
    the key, but a copied or forged entry must still never load). A bad
    entry is pruned and counted as a fallback, never raised."""
    global hits
    import jax
    bpath, mpath = _paths(key)
    manifest = _read_manifest(mpath)
    if manifest is None:
        if os.path.exists(bpath):           # blob without commit record
            _fallback(key, "missing_manifest")
        return None
    try:
        with open(bpath, "rb") as f:
            blob = f.read()
    except OSError:
        _fallback(key, "missing_blob")
        return None
    if zlib.crc32(blob) & 0xFFFFFFFF != manifest.get("crc"):
        _fallback(key, "crc_mismatch")
        return None
    if manifest.get("jax_version") != jax.__version__:
        _fallback(key, "stale_jax_version")
        return None
    if manifest.get("topology") != topology_fingerprint(mesh_shape):
        _fallback(key, "wrong_topology")
        return None
    hits += 1
    _count("hits")
    # LRU stamp + hit count: lock-free last-writer-wins manifest rewrite
    manifest["hits"] = int(manifest.get("hits", 0)) + 1
    manifest["last_used"] = time.time()
    try:
        _write_manifest(mpath, manifest)
    except OSError:
        pass
    return blob


def store(key: str, blob: bytes, kind: str = "", label: str = "",
          mesh_shape=None) -> bool:
    """Persist one entry crash-atomically. The manifest CRC is computed
    over the INTENDED bytes before the `compile_cache.write` fault site
    can mangle them, so a torn write is caught by the next lookup. Never
    raises; a failed store just means the next process compiles fresh."""
    global stores
    import jax
    if not _DIR:
        return False
    bpath, mpath = _paths(key)
    manifest = {
        "schema": _SCHEMA,
        "key": key,
        "kind": kind,
        "label": label,
        "bytes": len(blob),
        "crc": zlib.crc32(blob) & 0xFFFFFFFF,
        "jax_version": jax.__version__,
        "topology": topology_fingerprint(mesh_shape),
        "created": time.time(),
        "last_used": time.time(),
        "hits": 0,
    }
    if _faults._ENABLED:
        blob = _faults.mangle("compile_cache.write", blob)
    try:
        from ..framework.sharded_io import atomic_write
        os.makedirs(_DIR, exist_ok=True)
        atomic_write(bpath, blob, unique_tmp=True)
        _write_manifest(mpath, manifest)
    except OSError:
        return False
    stores += 1
    _count("stores")
    _enforce_cap()
    return True


def note_miss() -> None:
    global misses
    misses += 1
    _count("misses")


def note_export_skip(why: str = "") -> None:
    global export_skips
    export_skips += 1
    _count("export_skips")
    if _monitor._ENABLED and why:
        _monitor.log_event("compile_cache.export_skip", why=why[:200])


# ---- listing / gc / verify (the monitor CLI's `cache` subcommand) ----------

def entries(dirname: Optional[str] = None) -> List[Dict[str, Any]]:
    """Manifest-backed listing of every committed entry, LRU first."""
    d = dirname or _DIR
    out: List[Dict[str, Any]] = []
    if not d or not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        manifest = _read_manifest(os.path.join(d, name))
        if not manifest or "key" not in manifest:
            continue
        bpath = os.path.join(d, manifest["key"] + ".bin")
        try:
            nbytes = os.path.getsize(bpath)
        except OSError:
            nbytes = -1   # torn entry: manifest without blob
        row = dict(manifest)
        row["disk_bytes"] = nbytes
        row["age_s"] = max(0.0, time.time() - float(
            manifest.get("created", 0.0)))
        out.append(row)
    out.sort(key=lambda r: float(r.get("last_used", 0.0)))
    return out


def total_bytes(dirname: Optional[str] = None) -> int:
    return sum(max(0, e["disk_bytes"]) + len(json.dumps(e))
               for e in entries(dirname))


def gc(dirname: Optional[str] = None,
       cap_mb: Optional[float] = None) -> List[str]:
    """Evict least-recently-used entries until the directory fits the cap
    (`FLAGS_compile_cache_mb`). Returns evicted keys."""
    global evictions
    d = dirname or _DIR
    cap = float(_flags.flag("compile_cache_mb")) if cap_mb is None \
        else float(cap_mb)
    cap_bytes = int(cap * 1024 * 1024)
    rows = entries(d)
    used = sum(max(0, r["disk_bytes"]) for r in rows)
    evicted: List[str] = []
    for row in rows:                       # LRU first
        if used <= cap_bytes:
            break
        _prune(row["key"], d)
        used -= max(0, row["disk_bytes"])
        evicted.append(row["key"])
    if evicted:
        evictions += len(evicted)
        _count("evictions", len(evicted))
    return evicted


def _enforce_cap() -> None:
    try:
        gc()
    except Exception:
        pass


def verify(dirname: Optional[str] = None,
           prune: bool = True) -> Tuple[int, List[str]]:
    """CRC-check every entry; optionally prune corrupt/torn ones.
    Returns (ok_count, bad_keys)."""
    d = dirname or _DIR
    ok, bad = 0, []
    for row in entries(d):
        bpath, _ = _paths(row["key"], d)
        try:
            with open(bpath, "rb") as f:
                blob = f.read()
            good = zlib.crc32(blob) & 0xFFFFFFFF == row.get("crc")
        except OSError:
            good = False
        if good:
            ok += 1
        else:
            bad.append(row["key"])
            if prune:
                _prune(row["key"], d)
    return ok, bad


# ---- stats -----------------------------------------------------------------

def stats() -> Dict[str, int]:
    """Process-lifetime cache activity (plain ints — valid with the
    monitor off; `ServingEngine.stats()` embeds this dict)."""
    return {"hits": hits, "misses": misses, "fallbacks": fallbacks,
            "stores": stores, "evictions": evictions,
            "export_skips": export_skips}


def reset_stats() -> None:
    global hits, misses, fallbacks, stores, evictions, export_skips
    hits = misses = fallbacks = stores = evictions = export_skips = 0


def warm_start_report() -> Dict[str, Any]:
    """One-call warm-start verdict for a freshly spawned process: cache
    activity plus the `trace_compile` ledger counter (core/executable.py
    counts every traced build there). `warm` is the autoscaler's
    acceptance bit — the process served with ZERO traced compiles and at
    least one cache hit, i.e. scale-out actually exploited the
    persistent cache instead of paying cold compiles."""
    compiles = 0
    if _monitor._ENABLED:
        compiles = int(
            _monitor.snapshot()["counters"].get("trace_compile", 0))
    s = stats()
    return {"enabled": enabled(), "dir": cache_dir(),
            "trace_compile": compiles,
            "warm": bool(enabled() and compiles == 0 and s["hits"] > 0),
            **s}
