"""Device / Place abstraction.

Reference parity: `paddle/fluid/platform/place.h:1` (CPUPlace/CUDAPlace/...)
and `paddle.set_device` (`python/paddle/device/__init__.py`). On TPU the
device identity maps to a `jax.Device`; multi-chip identity is expressed via
`jax.sharding.Mesh` (see paddle_tpu.parallel), not per-op placement.
"""
from __future__ import annotations

import jax


class Place:
    """Tagged device identity."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform == self._jax_platform()]
        if not devs:
            # fall back to whatever the default backend exposes (CI without TPU)
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def _jax_platform(self):
        return {"cpu": "cpu", "tpu": "tpu", "gpu": "gpu"}.get(self.device_type, "cpu")


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


class CUDAPlace(Place):  # accepted for API compat; maps to gpu backend if present
    device_type = "gpu"


def _default_place() -> Place:
    try:
        plat = jax.default_backend()
    except Exception:  # pragma: no cover
        plat = "cpu"
    if plat == "tpu":
        return TPUPlace(0)
    if plat == "gpu":
        return CUDAPlace(0)
    return CPUPlace(0)


_CURRENT_PLACE = [None]


def set_device(device) -> Place:
    """paddle.set_device('tpu') / 'cpu' / 'tpu:0'."""
    if isinstance(device, Place):
        place = device
    else:
        spec = str(device).lower()
        idx = 0
        if ":" in spec:
            spec, sidx = spec.split(":", 1)
            idx = int(sidx)
        if spec in ("tpu", "xla"):
            place = TPUPlace(idx)
        elif spec in ("gpu", "cuda"):
            place = CUDAPlace(idx)
        elif spec == "cpu":
            place = CPUPlace(idx)
        else:
            raise ValueError(f"unknown device {device!r}")
    _CURRENT_PLACE[0] = place
    jax.config.update("jax_default_device", place.jax_device())
    return place


def get_device() -> str:
    p = get_place()
    return f"{p.device_type}:{p.device_id}"


def get_place() -> Place:
    if _CURRENT_PLACE[0] is None:
        _CURRENT_PLACE[0] = _default_place()
    return _CURRENT_PLACE[0]


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def device_count() -> int:
    return jax.device_count()


class CUDAPinnedPlace(Place):
    """Accepted for API compat (pinned host memory has no TPU analogue —
    host staging buffers are runtime-managed); treated as host placement."""

    def __init__(self):
        super().__init__("cpu", 0)


class NPUPlace(Place):
    """Accepted for API compat; maps onto the single accelerator backend."""

    def __init__(self, idx=0):
        super().__init__("npu", idx)
