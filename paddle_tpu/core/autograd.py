"""Imperative autograd: a reverse-mode tape over JAX VJPs.

Reference parity: this is the TPU-native answer to the dygraph stack —
`imperative/tracer.cc:172` (TraceOp records grad nodes) +
`imperative/basic_engine.cc:391` (reverse-topological execute) +
`imperative/gradient_accumulator.cc` (grad sums).

TPU-first design: instead of per-op CUDA grad kernels selected by a grad-op
registry, every traced op captures its VJP via `jax.vjp` at forward time.
Forward runs eagerly on the XLA backend (each primitive is compile-cached by
JAX); backward walks the tape in reverse creation order and feeds cotangents
through the stored VJP closures. Gradients accumulate on leaf tensors'
`.grad`, matching Paddle dygraph semantics (stop_gradient, leaf-only grads).
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp


class _State(threading.local):
    def __init__(self):
        self.enabled = True
        self.seq = 0
        # Live-node registry for introspection only (tape_size). Weak refs:
        # node lifetime is keyed to output-tensor reachability, so side
        # branches (metrics, logging) are GC'd when their tensors die instead
        # of accumulating forever on a global list.
        self.live: "weakref.WeakSet[Node]" = weakref.WeakSet()


_STATE = _State()


class Node:
    """One traced op: inputs, outputs, and the VJP closure linking them.

    Nodes are NOT held by any global structure (only weakly, for stats);
    the graph is reachable solely through output tensors' `_node` refs and
    `node.inputs -> tensor -> _node` chains. `seq` preserves creation order
    so backward can process in reverse-creation order without a tape list.
    """

    __slots__ = ("vjp_fn", "inputs", "outputs", "name", "seq", "fn",
                 "__weakref__")

    def __init__(self, vjp_fn, inputs, outputs, name, fn=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs      # list[Tensor] (diff inputs, positional)
        self.outputs = outputs    # list[Tensor] (diff outputs, positional)
        self.name = name
        self.fn = fn              # primal fn — kept for double grad (remat)
        _STATE.seq += 1
        self.seq = _STATE.seq


def is_grad_enabled() -> bool:
    return _STATE.enabled


def set_grad_enabled(mode: bool):
    _STATE.enabled = bool(mode)


class no_grad:
    """Context manager & decorator: disable tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _STATE.enabled
        _STATE.enabled = False
        return self

    def __exit__(self, *exc):
        _STATE.enabled = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*a, **kw):
            with no_grad():
                return fn(*a, **kw)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = _STATE.enabled
        _STATE.enabled = True
        return self


def apply_op(
    fn: Callable,
    diff_inputs: Sequence["Tensor"],  # noqa: F821
    name: str = "op",
    n_outs: int = 1,
) -> Any:
    """Run `fn(*arrays) -> array | tuple` over the diff inputs, recording a tape node
    when grad is enabled and any input requires grad.

    Returns raw jax output(s); wrapping into Tensor happens in the ops layer so
    this module stays free of Tensor construction policy.
    """
    arrays = tuple(t._value for t in diff_inputs)
    record = _STATE.enabled and any(not t.stop_gradient for t in diff_inputs)
    # Inside a jax trace (to_static), inputs are tracers: let JAX do the
    # differentiation; recording a tape of tracers would leak them.
    if record and any(isinstance(a, jax.core.Tracer) for a in arrays):
        record = False
    if not record:
        return fn(*arrays), None
    outs, vjp_fn = jax.vjp(fn, *arrays)
    return outs, vjp_fn


def record_node(vjp_fn, diff_inputs, out_tensors, name, fn=None):
    node = Node(vjp_fn, list(diff_inputs), list(out_tensors), name, fn=fn)
    for t in out_tensors:
        t._node = node
        t.stop_gradient = False
    _STATE.live.add(node)
    return node


def _collect(roots):
    """Walk ancestor nodes from root nodes; return them sorted newest-first."""
    needed = {}
    stack = [n for n in roots if n is not None]
    while stack:
        node = stack.pop()
        if id(node) in needed:
            continue
        needed[id(node)] = node
        for t in node.inputs:
            if t._node is not None and id(t._node) not in needed:
                stack.append(t._node)
    return sorted(needed.values(), key=lambda n: -n.seq)


def _accumulate(store: dict, tensor, value):
    # SelectedRows values accumulate row-form (SelectedRows.__add__ handles
    # sparse+sparse concat and sparse+dense densify); conversion to dense
    # happens only when a cotangent is CONSUMED by an upstream jnp vjp
    # (_dense_cot) — paddle.grad on a sparse leaf stays sparse.
    key = id(tensor)
    cur = store.get(key)
    store[key] = value if cur is None else cur + value


def _dense_cot(c):
    """Cotangent about to enter a jnp-based vjp: densify SelectedRows."""
    from .selected_rows import SelectedRows
    return c.to_dense() if isinstance(c, SelectedRows) else c


def backward(root, grad=None, retain_graph: bool = False):
    """Run the tape backward from `root` (paddle.Tensor.backward parity)."""
    if root._node is None:
        if not root.stop_gradient:
            g = jnp.ones_like(root._value) if grad is None else grad
            root.grad = (root.grad + g) if root.grad is not None else +g
        return

    if grad is None:
        if root._value.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad "
                f"(shape {root._value.shape})"
            )
        grad = jnp.ones_like(root._value)
    elif hasattr(grad, "_value"):
        grad = grad._value

    ordered = _collect([root._node])

    cot: dict = {id(root): grad}
    with no_grad():
        for node in ordered:
            out_cots = []
            any_live = False
            for t in node.outputs:
                c = cot.pop(id(t), None)
                if c is None:
                    c = jnp.zeros_like(t._value)
                else:
                    any_live = True
                out_cots.append(_dense_cot(c))
            if not any_live:
                continue
            in_cots = node.vjp_fn(tuple(out_cots) if len(out_cots) > 1 else out_cots[0])
            for t, c in zip(node.inputs, in_cots):
                if t.stop_gradient:
                    continue
                if t._node is None:  # leaf: accumulate .grad
                    from .selected_rows import SelectedRows
                    if isinstance(c, SelectedRows):
                        # sparse embedding grad: stays row-form; hooks see
                        # the SelectedRows; mixing with an existing dense
                        # grad densifies via __add__
                        for h in getattr(t, "_hooks", ()):
                            r = h(c)
                            if r is not None:
                                c = r._value if hasattr(r, "_value") else r
                        t.grad = c if t.grad is None else t.grad + c
                        continue
                    gc = c.astype(t._value.dtype) if c.dtype != t._value.dtype else c
                    for h in getattr(t, "_hooks", ()):
                        r = h(gc)
                        if r is not None:
                            gc = r._value if hasattr(r, "_value") else r
                    t.grad = gc if t.grad is None else t.grad + gc
                else:
                    _accumulate(cot, t, c)

    if not retain_graph:
        for n in ordered:
            for t in n.outputs:
                t._node = None
            n.vjp_fn = None
            n.inputs = n.outputs = ()
            _STATE.live.discard(n)


def grad_fn(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
            allow_unused=False):
    """paddle.grad parity (partial_grad_engine.cc): grads of outputs w.r.t.
    inputs without touching .grad. With create_graph=True the backward pass
    itself is RECORDED on the tape (each node's VJP replayed through its
    saved primal fn via jax.vjp — rematerialized), so the returned grads are
    differentiable again (double/higher-order grad)."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    ordered = _collect([o._node for o in outs])
    if create_graph:
        return _grad_create_graph(outs, ins, grad_outputs, allow_unused,
                                  ordered)

    cot: dict = {}
    for i, o in enumerate(outs):
        g = None
        if grad_outputs is not None and grad_outputs[i] is not None:
            g = getattr(grad_outputs[i], "_value", grad_outputs[i])
        else:
            g = jnp.ones_like(o._value)
        _accumulate(cot, o, g)

    results = [None] * len(ins)
    with no_grad():
        for node in ordered:
            out_cots, any_live = [], False
            for t in node.outputs:
                c = cot.get(id(t))
                if c is None:
                    c = jnp.zeros_like(t._value)
                else:
                    any_live = True
                out_cots.append(_dense_cot(c))
            if not any_live:
                continue
            in_cots = node.vjp_fn(tuple(out_cots) if len(out_cots) > 1 else out_cots[0])
            for t, c in zip(node.inputs, in_cots):
                _accumulate(cot, t, c)

    for i, t in enumerate(ins):
        c = cot.get(id(t))
        if c is None and not allow_unused:
            raise RuntimeError(f"input {i} unused in graph (allow_unused=False)")
        results[i] = c
    return results


def _grad_create_graph(outs, ins, grad_outputs, allow_unused, ordered):
    """Differentiable backward: cotangents are Tensors, every VJP step is a
    recorded op (remat through node.fn)."""
    from .tensor import Tensor
    from ..ops._dispatch import run_op

    cot: dict = {}  # id(tensor) -> Tensor cotangent

    def _acc(t, c):
        prev = cot.get(id(t))
        cot[id(t)] = c if prev is None else prev + c

    for i, o in enumerate(outs):
        if grad_outputs is not None and grad_outputs[i] is not None:
            g = grad_outputs[i]
            g = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g))
        else:
            g = Tensor(jnp.ones_like(o._value))
        _acc(o, g)

    for node in ordered:
        out_cots, any_live = [], False
        for t in node.outputs:
            c = cot.get(id(t))
            if c is None:
                c = Tensor(jnp.zeros_like(t._value))
            else:
                any_live = True
            out_cots.append(_dense_cot(c))
        if not any_live:
            continue
        if node.fn is None:
            raise NotImplementedError(
                f"double grad through '{node.name}': no primal fn recorded "
                "(PyLayer/custom node) — wrap it in a differentiable op")
        n_in, n_out, fn = len(node.inputs), len(node.outputs), node.fn

        def vjp_replay(*arrs, _fn=fn, _n=n_in, _nout=n_out):
            primals, cots = arrs[:_n], arrs[_n:]
            _, vjp = jax.vjp(_fn, *primals)
            res = vjp(tuple(cots) if _nout > 1 else cots[0])
            return tuple(res) if len(res) > 1 else res[0]

        in_cots = run_op(vjp_replay, list(node.inputs) + out_cots,
                         node.name + "_grad")
        in_cots = in_cots if isinstance(in_cots, tuple) else (in_cots,)
        for t, c in zip(node.inputs, in_cots):
            _acc(t, c)

    results = []
    for i, t in enumerate(ins):
        c = cot.get(id(t))
        if c is None and not allow_unused:
            raise RuntimeError(f"input {i} unused in graph (allow_unused=False)")
        results.append(c)
    return results


def clear_tape():
    """Break every live node's links so the whole recorded graph is freed."""
    for n in list(_STATE.live):
        for t in n.outputs:
            t._node = None
        n.vjp_fn = None
        n.inputs = n.outputs = ()
    _STATE.live = weakref.WeakSet()


def tape_size() -> int:
    return len(_STATE.live)
