"""Imperative autograd: a reverse-mode tape over JAX VJPs.

Reference parity: this is the TPU-native answer to the dygraph stack —
`imperative/tracer.cc:172` (TraceOp records grad nodes) +
`imperative/basic_engine.cc:391` (reverse-topological execute) +
`imperative/gradient_accumulator.cc` (grad sums).

TPU-first design: instead of per-op CUDA grad kernels selected by a grad-op
registry, every traced op captures its VJP via `jax.vjp` at forward time.
Forward runs eagerly on the XLA backend (each primitive is compile-cached by
JAX); backward walks the tape in reverse creation order and feeds cotangents
through the stored VJP closures. Gradients accumulate on leaf tensors'
`.grad`, matching Paddle dygraph semantics (stop_gradient, leaf-only grads).
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class _State(threading.local):
    def __init__(self):
        self.enabled = True
        self.seq = 0
        # Live-node registry for introspection only (tape_size). Weak refs:
        # node lifetime is keyed to output-tensor reachability, so side
        # branches (metrics, logging) are GC'd when their tensors die instead
        # of accumulating forever on a global list.
        self.live: "weakref.WeakSet[Node]" = weakref.WeakSet()


_STATE = _State()

# set by paddle_tpu.ops.lazy at import: backward()/paddle.grad are sync
# points for the lazy batching eager executor — the pending segment must
# flush (materializing outputs and patching _PendingVJP -> _JitVJP on the
# tape) before the walk starts
_LAZY = None


def _lazy_flush():
    if _LAZY is not None and _LAZY._ACTIVE:
        _LAZY.flush_pending()


class Node:
    """One traced op: inputs, outputs, and the VJP closure linking them.

    Nodes are NOT held by any global structure (only weakly, for stats);
    the graph is reachable solely through output tensors' `_node` refs and
    `node.inputs -> tensor -> _node` chains. `seq` preserves creation order
    so backward can process in reverse-creation order without a tape list.
    """

    __slots__ = ("vjp_fn", "inputs", "outputs", "name", "seq", "fn",
                 "__weakref__")

    def __init__(self, vjp_fn, inputs, outputs, name, fn=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs      # list[Tensor] (diff inputs, positional)
        self.outputs = outputs    # list[Tensor] (diff outputs, positional)
        self.name = name
        self.fn = fn              # primal fn — kept for double grad (remat)
        _STATE.seq += 1
        self.seq = _STATE.seq


def is_grad_enabled() -> bool:
    return _STATE.enabled


def set_grad_enabled(mode: bool):
    _STATE.enabled = bool(mode)


class no_grad:
    """Context manager & decorator: disable tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _STATE.enabled
        _STATE.enabled = False
        return self

    def __exit__(self, *exc):
        _STATE.enabled = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*a, **kw):
            with no_grad():
                return fn(*a, **kw)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = _STATE.enabled
        _STATE.enabled = True
        return self


# ---- eager dispatch cache -------------------------------------------------
# The reference's dygraph hot loop (`imperative/tracer.cc:172`) pays one
# kernel launch per op; our eager hot loop pays one jax.vjp RE-TRACE per op
# (~5-10ms of Python) plus per-primitive dispatch RTT on a tunneled device.
# Both collapse when the (forward, vjp) pair is traced ONCE per op closure
# and re-dispatched as a single cached XLA executable: `jax.jit` can return
# jax.vjp's function (it is a pytree of residual arrays over a static
# treedef), and a shared jitted applicator replays the backward.
#
# Cache key: the op closure's identity-by-VALUE — code object + frozen
# closure cells + frozen defaults. Closures capturing anything unhashable
# (arrays, Tensors, per-call lambdas) fall back to the uncached path, so
# caching can never alias two behaviorally different ops.

_JIT_CACHE: dict = {}
_UNJITTABLE: set = set()
_JIT_CACHE_CAP = 4096
from .random import TraceKeyError as _TraceKeyError  # noqa: E402

_BAILOUT_ERRORS = (jax.errors.TracerBoolConversionError,
                   jax.errors.ConcretizationTypeError,
                   jax.errors.TracerArrayConversionError,
                   jax.errors.TracerIntegerConversionError,
                   jax.errors.UnexpectedTracerError,
                   _TraceKeyError)


class _Uncacheable(Exception):
    pass


def _freeze(v):
    """Hashable value-token for a closure cell, or raise _Uncacheable."""
    if isinstance(v, (str, int, float, bool, bytes, complex, type(None))):
        return v
    if isinstance(v, np.dtype):
        return ("dt", v.str)
    if isinstance(v, tuple):
        return ("t",) + tuple(_freeze(x) for x in v)
    if isinstance(v, list):
        return ("l",) + tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return ("s",) + tuple(sorted((_freeze(x) for x in v), key=repr))
    if isinstance(v, dict):
        return ("d",) + tuple((k, _freeze(x)) for k, x in sorted(v.items()))
    if isinstance(v, functools.partial):
        return ("p", _freeze(v.func), _freeze(v.args), _freeze(v.keywords))
    if callable(v):
        qn = getattr(v, "__qualname__", None)
        if qn is not None and "<locals>" not in qn \
                and getattr(v, "__module__", None):
            return ("f", v.__module__, qn)  # stable module-level callable
        if qn is None and type(v).__name__ == "ufunc" \
                and getattr(v, "__name__", None):
            # jnp.add/multiply/... are jax.numpy.ufunc instances: no
            # __qualname__, but singleton, stateless and named — a stable
            # token (this makes binary_op(jnp.<ufunc>) dispatch-cacheable)
            return ("uf", getattr(type(v), "__module__", "jnp"), v.__name__)
    raise _Uncacheable


def _ambient_key():
    """Global state op fns may read at trace time (AMP autocast regime,
    matmul precision flag, default dtype) — it must key the cache, or a fn
    traced under one regime would replay under another."""
    from ..amp.state import amp_state
    from . import flags as _flags
    from .dtype import get_default_dtype
    s = amp_state()
    return (s.enabled, str(s.dtype), s.level,
            _flags.flag("tpu_matmul_precision"), get_default_dtype())


def _fn_key(fn):
    # INVARIANT (ADVICE r4): this key freezes closure cells, defaults and
    # the fixed _ambient_key() tuple, but NOT module-level globals. Op fns
    # routed through the dispatch cache must not read mutable globals
    # outside _ambient_key — any new config flag an op fn consults at
    # trace time MUST be added to _ambient_key, or a cached executable
    # traced under the old value would silently replay after it changes.
    code = getattr(fn, "__code__", None)
    if code is None:
        return ("fn", _freeze(fn), _ambient_key())
    frozen = tuple(_freeze(c.cell_contents) for c in (fn.__closure__ or ()))
    dflt = _freeze(fn.__defaults__) if fn.__defaults__ else None
    kwd = _freeze(fn.__kwdefaults__) if getattr(fn, "__kwdefaults__", None) \
        else None
    return ("code", code, frozen, dflt, kwd, _ambient_key())


def _cached_jit(fn, kind, build=None):
    """Jitted forward (kind='primal') or forward+vjp (kind='vjp') for fn,
    or None when fn's closure can't be value-keyed."""
    try:
        key = (kind, _fn_key(fn))
    except _Uncacheable:
        return None, None
    if key in _UNJITTABLE:
        return None, None
    jf = _JIT_CACHE.get(key)
    if jf is None:
        from .. import monitor as _monitor
        if _monitor._ENABLED:
            _monitor.count("autograd.jit_cache_miss")
        if len(_JIT_CACHE) >= _JIT_CACHE_CAP:
            _JIT_CACHE.clear()
        if build is not None:
            jf = build()
        elif kind == "vjp":
            jf = jax.jit(lambda *a: jax.vjp(fn, *a))
        else:
            jf = jax.jit(fn)
        _JIT_CACHE[key] = jf
    return jf, key


@functools.lru_cache(maxsize=1)
def _bwd_apply():
    # jit cache specializes on the VJP pytree's treedef (= its backward
    # jaxpr), which is stable across calls of the same cached forward.
    return jax.jit(lambda vjp_fn, cts: vjp_fn(cts))


class _JitVJP:
    """VJP wrapper routing application through the shared jitted applicator
    so backward is one executable dispatch instead of an op-by-op walk.

    `inexact` (when set) marks which of the op's positional inputs were
    differentiated; integer/bool inputs got no cotangent slot and are
    reported as None (their tape entries are stop_gradient and skipped).
    `treedef` (when set) is the NESTED output structure of the traced
    function: the tape stores flat leaf tensors, so the flat cotangents
    are unflattened back before hitting the raw vjp (static-program
    captures of layers returning nested tuples, e.g. LSTM's
    (out, (h, c)))."""

    __slots__ = ("raw", "inexact", "treedef")

    def __init__(self, raw, inexact=None, treedef=None):
        self.raw = raw
        self.inexact = inexact
        self.treedef = treedef

    def __call__(self, cts):
        if self.treedef is not None:
            flat = list(cts) if isinstance(cts, tuple) else [cts]
            cts = jax.tree_util.tree_unflatten(self.treedef, flat)
        try:
            part = _bwd_apply()(self.raw, cts)
        except _BAILOUT_ERRORS:
            part = self.raw(cts)
        if self.inexact is None:
            return part
        it = iter(part)
        return tuple(next(it) if f else None for f in self.inexact)


def _split_vjp_builder(fn, inexact):
    """fn with integer args: differentiate only the inexact positions,
    threading the integer arrays through as plain jit arguments."""
    didx = tuple(i for i, f in enumerate(inexact) if f)

    def wrapper(*args):
        def g(*diff):
            it = iter(diff)
            full = [next(it) if inexact[i] else args[i]
                    for i in range(len(args))]
            return fn(*full)
        return jax.vjp(g, *(args[i] for i in didx))

    return wrapper


def apply_op(
    fn: Callable,
    diff_inputs: Sequence["Tensor"],  # noqa: F821
    name: str = "op",
    n_outs: int = 1,
) -> Any:
    """Run `fn(*arrays) -> array | tuple` over the diff inputs, recording a tape node
    when grad is enabled and any input requires grad.

    Returns raw jax output(s); wrapping into Tensor happens in the ops layer so
    this module stays free of Tensor construction policy.
    """
    arrays = tuple(t._value for t in diff_inputs)
    record = _STATE.enabled and any(not t.stop_gradient for t in diff_inputs)
    # Inside a jax trace (to_static), inputs are tracers: let JAX do the
    # differentiation; recording a tape of tracers would leak them.
    tracing = any(isinstance(a, jax.core.Tracer) for a in arrays)
    if record and tracing:
        record = False
    if not record:
        if tracing:
            return fn(*arrays), None
        jf, key = _cached_jit(fn, "primal")
        if jf is not None:
            try:
                return jf(*arrays), None
            except _BAILOUT_ERRORS:
                _UNJITTABLE.add(key)
        return fn(*arrays), None
    inexact = tuple(bool(jnp.issubdtype(a.dtype, jnp.inexact))
                    for a in arrays)
    if all(inexact):
        jf, key = _cached_jit(fn, "vjp")
        if jf is not None:
            try:
                outs, vjp_fn = jf(*arrays)
                return outs, _JitVJP(vjp_fn)
            except _BAILOUT_ERRORS:
                _UNJITTABLE.add(key)
    elif all(t.stop_gradient or f
             for t, f in zip(diff_inputs, inexact)):
        # integer inputs (labels, indices) ride through as jit args; only
        # the float positions are differentiated — no float0 round-trip.
        jf, key = _cached_jit(fn, ("vjp_split", inexact),
                              build=lambda f=fn: jax.jit(
                                  _split_vjp_builder(f, inexact)))
        if jf is not None:
            try:
                outs, vjp_fn = jf(*arrays)
                return outs, _JitVJP(vjp_fn, inexact)
            except _BAILOUT_ERRORS:
                _UNJITTABLE.add(key)
    outs, vjp_fn = jax.vjp(fn, *arrays)
    return outs, vjp_fn


def record_node(vjp_fn, diff_inputs, out_tensors, name, fn=None):
    node = Node(vjp_fn, list(diff_inputs), list(out_tensors), name, fn=fn)
    for t in out_tensors:
        t._node = node
        t.stop_gradient = False
    _STATE.live.add(node)
    return node


def _collect(roots):
    """Walk ancestor nodes from root nodes; return them sorted newest-first."""
    needed = {}
    stack = [n for n in roots if n is not None]
    while stack:
        node = stack.pop()
        if id(node) in needed:
            continue
        needed[id(node)] = node
        for t in node.inputs:
            if t._node is not None and id(t._node) not in needed:
                stack.append(t._node)
    return sorted(needed.values(), key=lambda n: -n.seq)


def _accumulate(store: dict, tensor, value):
    # SelectedRows values accumulate row-form (SelectedRows.__add__ handles
    # sparse+sparse concat and sparse+dense densify); conversion to dense
    # happens only when a cotangent is CONSUMED by an upstream jnp vjp
    # (_dense_cot) — paddle.grad on a sparse leaf stays sparse.
    if value is None:  # integer input skipped by a split vjp
        return
    key = id(tensor)
    cur = store.get(key)
    store[key] = value if cur is None else cur + value


def _dense_cot(c):
    """Cotangent about to enter a jnp-based vjp: densify SelectedRows."""
    from .selected_rows import SelectedRows
    return c.to_dense() if isinstance(c, SelectedRows) else c


# ---- fused tape walk ---------------------------------------------------
# The eager walk dispatches one jitted vjp per node (plus per-leaf adds):
# on a remote/tunnel target that is one RTT per op. When the whole tape is
# _JitVJP nodes (the common repeated-training-step shape), the walk itself
# is pure orchestration of arrays — so it can run INSIDE one jit, keyed by
# the tape's structure: each step's tensors are new objects, but the
# wiring (who feeds whom) repeats, and the vjp residual pytrees ride in as
# jit arguments. One executable per backward instead of N.
_FUSED_BWD_CACHE: dict = {}
_FUSED_BWD_SEEN: dict = {}
_FUSED_BWD_MAX = 256
_FUSED_BWD_THRESHOLD = 2   # compile only for REPEATING tape structures


def _fused_backward_try(root, grad, ordered):
    """Returns list of (leaf_tensor, grad_array) or None if ineligible."""
    from .selected_rows import SelectedRows
    # slot assignment: every tensor seen gets an integer slot
    slots: dict = {}
    tensors_by_slot: dict = {}

    def slot_of(t):
        s = slots.get(id(t))
        if s is None:
            s = slots[id(t)] = len(slots)
            tensors_by_slot[s] = t
        return s

    structure = []

    for node in ordered:
        if not isinstance(node.vjp_fn, _JitVJP):
            return None
        for t in node.inputs:
            if (not t.stop_gradient and t._node is None
                    and getattr(t, "_hooks", ())):
                return None        # leaf hooks: keep the eager walk
            if isinstance(t.grad, SelectedRows):
                return None
        out_slots = tuple(
            (slot_of(t), tuple(t._value.shape), str(t._value.dtype))
            for t in node.outputs)
        in_slots = tuple(
            (slot_of(t), bool(t.stop_gradient), t._node is None,
             str(t._value.dtype))
            for t in node.inputs)
        structure.append((node.name, node.vjp_fn.inexact,
                          node.vjp_fn.treedef, out_slots, in_slots))

    key = (len(slots), slot_of(root), tuple(structure))
    fn = _FUSED_BWD_CACHE.get(key)
    if fn is None:
        # gate the whole-tape compile on structure REPETITION (mirror of
        # the forward's _AUTOJIT_THRESHOLD): a varying-shape / dynamic-
        # graph workload would otherwise pay a full XLA compile on every
        # novel backward instead of the already-compiled eager walk
        seen = _FUSED_BWD_SEEN.get(key, 0) + 1
        if len(_FUSED_BWD_SEEN) >= 4 * _FUSED_BWD_MAX:
            _FUSED_BWD_SEEN.clear()
        _FUSED_BWD_SEEN[key] = seen
        if seen < _FUSED_BWD_THRESHOLD:
            return None
        if len(_FUSED_BWD_CACHE) >= _FUSED_BWD_MAX:
            _FUSED_BWD_CACHE.clear()
        struct = tuple(structure)
        root_slot = slot_of(root)

        def walk(g_root, raws):
            cot: dict = {root_slot: g_root}
            leaf_out: dict = {}
            for (name, inexact, treedef, out_slots, in_slots), raw in zip(
                    struct, raws):
                out_cots = []
                any_live = False
                for s, shp, dt in out_slots:
                    c = cot.pop(s, None)
                    if c is None:
                        c = jnp.zeros(shp, dt)
                    else:
                        any_live = True
                    out_cots.append(c)
                if not any_live:
                    continue
                if treedef is not None:
                    part = raw(jax.tree_util.tree_unflatten(treedef,
                                                            out_cots))
                else:
                    part = raw(tuple(out_cots) if len(out_cots) > 1
                               else out_cots[0])
                if inexact is not None:
                    it = iter(part)
                    part = tuple(next(it) if f else None for f in inexact)
                for (s, stop, is_leaf, dt), c in zip(in_slots, part):
                    if stop or c is None:
                        continue
                    if is_leaf:
                        c = c.astype(dt) if str(c.dtype) != dt else c
                        leaf_out[s] = (leaf_out[s] + c) if s in leaf_out \
                            else c
                    else:
                        cot[s] = (cot[s] + c) if s in cot else c
            return leaf_out

        fn = _FUSED_BWD_CACHE[key] = jax.jit(walk)
    raws = [n.vjp_fn.raw for n in ordered]
    try:
        leaf_grads = fn(grad, raws)
    except _BAILOUT_ERRORS:
        return None
    return [(tensors_by_slot[s], g) for s, g in leaf_grads.items()]


def backward(root, grad=None, retain_graph: bool = False):
    """Run the tape backward from `root` (paddle.Tensor.backward parity)."""
    from .. import monitor as _monitor
    if not _monitor._ENABLED:
        return _backward_impl(root, grad, retain_graph)
    import time as _time
    _t0 = _time.time()
    try:
        return _backward_impl(root, grad, retain_graph)
    finally:
        _monitor.count("autograd.backward_count")
        _monitor.observe("autograd.backward_dur", _time.time() - _t0)


def _backward_impl(root, grad=None, retain_graph: bool = False):
    _lazy_flush()
    if root._node is None:
        if not root.stop_gradient:
            g = jnp.ones_like(root._value) if grad is None else grad
            root.grad = (root.grad + g) if root.grad is not None else +g
        return

    if grad is None:
        if root._value.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad "
                f"(shape {root._value.shape})"
            )
        grad = jnp.ones_like(root._value)
    elif hasattr(grad, "_value"):
        grad = grad._value

    ordered = _collect([root._node])
    from .. import monitor as _monitor
    if _monitor._ENABLED:
        _monitor.count("autograd.nodes_walked", len(ordered))

    fused = _fused_backward_try(root, grad, ordered)
    if fused is not None:
        if _monitor._ENABLED:
            _monitor.count("autograd.fused_backward")
        for t, g in fused:
            t.grad = g if t.grad is None else t.grad + g
        if not retain_graph:
            for n in ordered:
                for t in n.outputs:
                    t._node = None
                n.vjp_fn = None
                n.inputs = n.outputs = ()
                _STATE.live.discard(n)
        return

    cot: dict = {id(root): grad}
    with no_grad():
        for node in ordered:
            out_cots = []
            any_live = False
            for t in node.outputs:
                c = cot.pop(id(t), None)
                if c is None:
                    c = jnp.zeros_like(t._value)
                else:
                    any_live = True
                out_cots.append(_dense_cot(c))
            if not any_live:
                continue
            in_cots = node.vjp_fn(tuple(out_cots) if len(out_cots) > 1 else out_cots[0])
            for t, c in zip(node.inputs, in_cots):
                if t.stop_gradient:
                    continue
                if t._node is None:  # leaf: accumulate .grad
                    from .selected_rows import SelectedRows
                    if isinstance(c, SelectedRows):
                        # sparse embedding grad: stays row-form; hooks see
                        # the SelectedRows; mixing with an existing dense
                        # grad densifies via __add__
                        for h in getattr(t, "_hooks", ()):
                            r = h(c)
                            if r is not None:
                                c = r._value if hasattr(r, "_value") else r
                        t.grad = c if t.grad is None else t.grad + c
                        continue
                    gc = c.astype(t._value.dtype) if c.dtype != t._value.dtype else c
                    for h in getattr(t, "_hooks", ()):
                        r = h(gc)
                        if r is not None:
                            gc = r._value if hasattr(r, "_value") else r
                    t.grad = gc if t.grad is None else t.grad + gc
                else:
                    _accumulate(cot, t, c)

    if not retain_graph:
        for n in ordered:
            for t in n.outputs:
                t._node = None
            n.vjp_fn = None
            n.inputs = n.outputs = ()
            _STATE.live.discard(n)


def grad_fn(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
            allow_unused=False):
    """paddle.grad parity (partial_grad_engine.cc): grads of outputs w.r.t.
    inputs without touching .grad. With create_graph=True the backward pass
    itself is RECORDED on the tape (each node's VJP replayed through its
    saved primal fn via jax.vjp — rematerialized), so the returned grads are
    differentiable again (double/higher-order grad)."""
    _lazy_flush()
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    ordered = _collect([o._node for o in outs])
    if create_graph:
        return _grad_create_graph(outs, ins, grad_outputs, allow_unused,
                                  ordered)

    cot: dict = {}
    for i, o in enumerate(outs):
        g = None
        if grad_outputs is not None and grad_outputs[i] is not None:
            g = getattr(grad_outputs[i], "_value", grad_outputs[i])
        else:
            g = jnp.ones_like(o._value)
        _accumulate(cot, o, g)

    results = [None] * len(ins)
    with no_grad():
        for node in ordered:
            out_cots, any_live = [], False
            for t in node.outputs:
                c = cot.get(id(t))
                if c is None:
                    c = jnp.zeros_like(t._value)
                else:
                    any_live = True
                out_cots.append(_dense_cot(c))
            if not any_live:
                continue
            in_cots = node.vjp_fn(tuple(out_cots) if len(out_cots) > 1 else out_cots[0])
            for t, c in zip(node.inputs, in_cots):
                _accumulate(cot, t, c)

    for i, t in enumerate(ins):
        c = cot.get(id(t))
        if c is None and not allow_unused:
            raise RuntimeError(f"input {i} unused in graph (allow_unused=False)")
        results[i] = c
    return results


def _grad_create_graph(outs, ins, grad_outputs, allow_unused, ordered):
    """Differentiable backward: cotangents are Tensors, every VJP step is a
    recorded op (remat through node.fn)."""
    from .tensor import Tensor
    from ..ops._dispatch import run_op

    cot: dict = {}  # id(tensor) -> Tensor cotangent

    def _acc(t, c):
        prev = cot.get(id(t))
        cot[id(t)] = c if prev is None else prev + c

    for i, o in enumerate(outs):
        if grad_outputs is not None and grad_outputs[i] is not None:
            g = grad_outputs[i]
            g = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g))
        else:
            g = Tensor(jnp.ones_like(o._value))
        _acc(o, g)

    for node in ordered:
        out_cots, any_live = [], False
        for t in node.outputs:
            c = cot.get(id(t))
            if c is None:
                c = Tensor(jnp.zeros_like(t._value))
            else:
                any_live = True
            out_cots.append(_dense_cot(c))
        if not any_live:
            continue
        if node.fn is None:
            raise NotImplementedError(
                f"double grad through '{node.name}': no primal fn recorded "
                "(PyLayer/custom node) — wrap it in a differentiable op")
        n_in, n_out, fn = len(node.inputs), len(node.outputs), node.fn

        def vjp_replay(*arrs, _fn=fn, _n=n_in, _nout=n_out):
            primals, cots = arrs[:_n], arrs[_n:]
            _, vjp = jax.vjp(_fn, *primals)
            res = vjp(tuple(cots) if _nout > 1 else cots[0])
            return tuple(res) if len(res) > 1 else res[0]

        in_cots = run_op(vjp_replay, list(node.inputs) + out_cots,
                         node.name + "_grad")
        in_cots = in_cots if isinstance(in_cots, tuple) else (in_cots,)
        for t, c in zip(node.inputs, in_cots):
            _acc(t, c)

    results = []
    for i, t in enumerate(ins):
        c = cot.get(id(t))
        if c is None and not allow_unused:
            raise RuntimeError(f"input {i} unused in graph (allow_unused=False)")
        results.append(c)
    return results


def clear_tape():
    """Break every live node's links so the whole recorded graph is freed."""
    for n in list(_STATE.live):
        for t in n.outputs:
            t._node = None
        n.vjp_fn = None
        n.inputs = n.outputs = ()
    _STATE.live = weakref.WeakSet()


def tape_size() -> int:
    return len(_STATE.live)
