from . import autograd, dtype, flags, place, random  # noqa: F401
from .tensor import Tensor, Parameter  # noqa: F401
