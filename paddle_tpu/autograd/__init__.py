"""paddle.autograd parity: PyLayer + functional jacobian/hessian/vjp/jvp.

Reference parity: `python/paddle/autograd/py_layer.py` and
`autograd/functional.py:87-807`. The functional transforms delegate to JAX's
native machinery (exact, composable — stronger than the reference's
double-grad path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd as _engine
from ..core.autograd import backward, no_grad  # noqa: F401
from ..core.tensor import Tensor
from ..ops._dispatch import run_op


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        """Method, matching Paddle's ctx.saved_tensor() call convention."""
        return self._saved

    saved_tensors = saved_tensor


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined forward/backward. Usage matches paddle:

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x): ...
        @staticmethod
        def backward(ctx, dy): ...
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        tensors = [args[i] for i in tensor_idx]

        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (list, tuple))
        outs = list(out) if multi else [out]

        record = _engine.is_grad_enabled() and any(not t.stop_gradient for t in tensors)
        if record:
            def vjp_fn(cots):
                cots = cots if isinstance(cots, tuple) else (cots,)
                with no_grad():
                    gin = cls.backward(ctx, *[Tensor(c) for c in cots])
                gin = gin if isinstance(gin, (list, tuple)) else (gin,)
                garr = [g._value if isinstance(g, Tensor) else g for g in gin]
                # map back to positional tensor inputs
                if len(garr) == len(tensors):
                    return tuple(garr)
                return tuple(garr[:len(tensors)])

            node_out = [Tensor(o._value) if isinstance(o, Tensor) else Tensor(o)
                        for o in outs]
            _engine.record_node(vjp_fn, tensors, node_out, cls.__name__)
            outs = node_out
        return tuple(outs) if multi else outs[0]

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError


def _functionalize(func):
    """Wrap a Tensor->Tensor python function as array->array for jax."""

    def fn(*arrays):
        out = func(*[Tensor(a) for a in arrays])
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    return fn


def _arrs(xs):
    if isinstance(xs, (list, tuple)):
        return [x._value if isinstance(x, Tensor) else x for x in xs]
    return [xs._value if isinstance(xs, Tensor) else xs]


def jacobian(func, xs, create_graph=False, allow_unused=False):
    arrays = _arrs(xs)
    fn = _functionalize(func)
    jac = jax.jacrev(fn, argnums=tuple(range(len(arrays))))(*arrays)
    if not isinstance(xs, (list, tuple)):
        return Tensor(jac[0])
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    arrays = _arrs(xs)
    fn = _functionalize(func)
    h = jax.hessian(fn, argnums=tuple(range(len(arrays))))(*arrays)
    if not isinstance(xs, (list, tuple)):
        return Tensor(h[0][0])
    return tuple(tuple(Tensor(c) for c in row) for row in h)


def vjp(func, xs, v=None):
    arrays = _arrs(xs)
    fn = _functionalize(func)
    out, vjp_fn = jax.vjp(fn, *arrays)
    if v is None:
        cot = jnp.ones_like(out)
    else:
        cot = v._value if isinstance(v, Tensor) else v
    grads = vjp_fn(cot)
    gout = [Tensor(g) for g in grads]
    return Tensor(out), (gout if isinstance(xs, (list, tuple)) else gout[0])


def jvp(func, xs, v=None):
    arrays = _arrs(xs)
    fn = _functionalize(func)
    tangents = [jnp.ones_like(a) for a in arrays] if v is None else \
        [t._value if isinstance(t, Tensor) else t for t in (v if isinstance(v, (list, tuple)) else [v])]
    out, tangent = jax.jvp(fn, tuple(arrays), tuple(tangents))
    return Tensor(out), Tensor(tangent)
