"""Pallas TPU flash-attention (forward kernel + recompute backward).

Reference parity: the reference's fused attention
(`operators/fused/fused_attention_op.cu`, `fmha_ref.h`) is an UNFUSED-softmax
FMHA; this kernel is the TPU-native upgrade: online-softmax tiling keeps the
S×S score matrix out of HBM entirely (O(S) memory), q/k/v tiles stream
HBM→VMEM and hit the MXU per block.

Grid: (batch*heads, q_blocks); inner fori_loop over k blocks with f32
running (max, sumexp, acc) carries. Causal masking prunes whole k-blocks via
the loop trip count. Backward recomputes through the XLA reference path
(flash-bwd kernel planned next round).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)          # [Bq, D]
    block_q = q.shape[0]
    n_kb = seq_len // block_k

    if causal:
        # highest k-block index that contains any unmasked key for this q block
        kmax = ((qi + 1) * block_q + block_k - 1) // block_k
        kmax = jnp.minimum(kmax, n_kb)
    else:
        kmax = n_kb

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)  # [Bk, D]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [Bq,Bk]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, -1e30)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, kmax, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, *, causal, block_q, block_k, interpret):
    """q/k/v: [BH, S, D] -> out [BH, S, D]."""
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=s)
    grid = (bh, s // block_q)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


def _reference_bhsd(q, k, v, causal):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bsd,btd->bst", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        n = s.shape[-1]
        mask = jnp.tril(jnp.ones((s.shape[-2], n), dtype=bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bst,btd->bsd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_bhsd(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret)


def _flash_core_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd_bhsd(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, interpret=interpret)
    return out, (q, k, v)


def _flash_core_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _reference_bhsd(a, b, c, causal), q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention_arrays(q, k, v, causal=False, block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K):
    """q/k/v: [B, S, H, D] (paddle layout). Returns [B, S, H, D]."""
    b, s, h, d = q.shape
    interpret = jax.default_backend() != "tpu"

    def to_bhsd(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)

    # pad seq to a block multiple (masked out by softmax via -inf scores)
    bq = min(block_q, max(128, 1 << (s - 1).bit_length()) if s < block_q else block_q)
    pad = (-s) % min(bq, block_k if s >= block_k else s)
    qb, kb_, vb = to_bhsd(q), to_bhsd(k), to_bhsd(v)
    if pad:
        # fall back to reference for ragged lengths (rare; pad-free path planned)
        out = _reference_bhsd(qb, kb_, vb, causal)
    else:
        out = _flash_core(qb, kb_, vb, causal, bq, min(block_k, s), interpret)
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)


def flash_attention(q, k, v, causal=False, block_q=DEFAULT_BLOCK_Q,
                    block_k=DEFAULT_BLOCK_K):
    """Tensor-level entry (records one tape node; used by nn attention)."""
    from ..ops._dispatch import ensure_tensor, run_op
    q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
    return run_op(
        lambda a, b, c: flash_attention_arrays(a, b, c, causal=causal,
                                               block_q=block_q, block_k=block_k),
        [q, k, v], "flash_attention")
