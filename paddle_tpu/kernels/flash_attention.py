"""Pallas TPU flash-attention (forward + backward kernels).

Reference parity: the reference's fused attention
(`operators/fused/fused_attention_op.cu`, `fmha_ref.h`) is an UNFUSED-softmax
FMHA; this kernel is the TPU-native upgrade: online-softmax tiling keeps the
S×S score matrix out of HBM entirely (O(S) memory), q/k/v tiles stream
HBM→VMEM and hit the MXU per block.

Forward grid: (batch*heads, q_blocks); inner fori_loop over k blocks with
f32 running (max, sumexp, acc) carries; also emits per-row logsumexp.
Causal masking prunes whole k-blocks via the loop trip count.

Backward: two kernels, both recomputing p = exp(s - lse) inside the kernel
from the saved logsumexp (no S×S materialization, f32 accumulators):
  - dq kernel, grid (BH, q_blocks): loops k blocks, dq += ds @ K.
  - dk/dv kernel, grid (BH, k_blocks): loops q blocks (causal: starting at
    the first unmasked q block), dv += pᵀ @ dO, dk += dsᵀ @ Q.
where ds = p * (dO·Vᵀ − delta), delta = rowsum(dO ∘ O) precomputed in XLA
(semantics oracle: `fmha_ref.h` softmax-grad algebra).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# measured on v5e (fwd+bwd, causal, bh12 d64): 512-blocks beat 256 by ~26%
# at seq 8192 (34.9 vs 27.7 steps/s; fused-XLA reference 14.9)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512

# The softmax runs in the BASE-2 domain: s2 = s * log2(e), p = exp2(s2 - m2).
# log2(e) folds into the scale multiply that was already there, so exp2
# replaces exp for free — and at these tile shapes the kernel is VPU-bound
# (each 512x512 tile costs ~0.7us of MXU but ~1us of VPU softmax work), so
# every VPU pass shaved shows up end to end. The emitted lse converts back
# to natural-log units (lse = ln2*m2 + log(l)) so ring-merge/consumers see
# the standard quantity.
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


# Loop structure shared by every kernel here: the k-block (or q-block)
# loop runs in groups of `unroll` tiles per fori_loop iteration. With one
# tile per iteration the carry (m/l/acc or dq) serializes each tile's MXU
# dot behind the previous tile's VPU softmax — measured fwd MFU 0.19 at
# d64/s8192. Unrolling U tiles per body lets Mosaic's VLIW scheduler issue
# tile i+1's dot while tile i's exp/max runs (fwd 0.19 -> 0.30 from
# unrolling alone). Groups stay ALIGNED (trip counts in units of U, with
# n_blocks % U == 0 enforced by the dispatcher), so a group that overruns
# the causal frontier simply has its extra tiles fully masked — the
# online-softmax identities absorb them (p == 0, alpha == 1).

def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k,
               seq_len, unroll, heads, local_softmax):
    qi = pl.program_id(1)
    # dots run in the INPUT dtype (bf16 hits the full-rate MXU path; the
    # f32 accumulate comes from preferred_element_type) — upcasting q/k/v
    # first would silently put every matmul on the slow fp32 MXU path
    block_q = q_ref.shape[1]
    n_kb = seq_len // block_k
    s2scale = scale * _LOG2E
    U = unroll
    G = heads                                 # bh slices per grid step

    def tile(g, kb, carry, masked):
        # Two softmax formulations, picked per head_dim by the dispatcher:
        # - local_softmax (d>=128): normalize against the tile's LOCAL row
        #   max so the [Bq,Bk] exp and both dots have no data dependence on
        #   the carry (tile i+1's dots issue under tile i's exp); the carry
        #   merge (online-softmax segment merge) touches only [Bq,1]/[Bq,D]
        #   vectors. Measured +9% fwd at d128/s8192.
        # - running max (d<64..127): the classic chain; the extra [Bq,D]
        #   merge multiplies of the local form cost more than the overlap
        #   buys when D is narrow. Measured +10% fwd at d64/s8192.
        m_run, l_run, acc = carry
        k = k_ref[g, pl.ds(kb * block_k, block_k), :]  # [Bk, D]
        v = v_ref[g, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q_ref[g], k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * s2scale
        if masked:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, -1e30)
        if local_softmax:
            m_t = jnp.max(s, axis=1, keepdims=True)
            p = jnp.exp2(s - m_t)
            l_t = jnp.sum(p, axis=1, keepdims=True)
            acc_t = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_new = jnp.maximum(m_run, m_t)
            alpha = jnp.exp2(m_run - m_new)
            # fully-masked overrun tiles: m_t == -1e30 -> beta == 0 wipes
            # the garbage p == exp2(0) == 1 rows out of the merge
            beta = jnp.exp2(m_t - m_new)
            l_new = l_run * alpha + l_t * beta
            acc = acc * alpha + acc_t * beta
            return m_new, l_new, acc
        m_new = jnp.maximum(m_run, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_run - m_new)
        l_new = l_run * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    def group(gi, carry, masked):
        # G heads x U k-blocks of INDEPENDENT tiles per loop body — both
        # give the VLIW scheduler dot/softmax work to interleave
        out = []
        for g in range(G):
            c = carry[g]
            for j in range(U):
                c = tile(g, gi * U + j, c, masked)
            out.append(c)
        return tuple(out)

    d = q_ref.shape[2]
    carry = tuple((jnp.full((block_q, 1), -1e30, jnp.float32),
                   jnp.zeros((block_q, 1), jnp.float32),
                   jnp.zeros((block_q, d), jnp.float32)) for _ in range(G))
    if causal:
        # diagonal split: k-block groups strictly below the diagonal skip
        # the iota/compare/select VPU passes; groups touching the diagonal
        # mask (including any aligned overrun past kmax, absorbed as p=0).
        n_full = (qi * block_q) // block_k
        kmax = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k, n_kb)
        nf_g = n_full // U
        ng = (kmax + U - 1) // U
        carry = jax.lax.fori_loop(0, nf_g,
                                  lambda gi, c: group(gi, c, False), carry)
        carry = jax.lax.fori_loop(nf_g, ng,
                                  lambda gi, c: group(gi, c, True), carry)
    else:
        carry = jax.lax.fori_loop(0, n_kb // U,
                                  lambda gi, c: group(gi, c, False), carry)
    for g in range(G):
        m, l, acc = carry[g]
        lsafe = jnp.maximum(l, 1e-30)
        o_ref[g] = (acc / lsafe).astype(o_ref.dtype)
        # lse carried as [BH, 1, S] so the (sublane, lane) dims of every
        # block are (1, block_q) with sublane == full array dim (Mosaic
        # tiling rule)
        lse_ref[g, 0] = (m * _LN2 + jnp.log(lsafe))[:, 0]



def _pick_unroll(n_blocks, tile_bytes, cap=4 * 2 ** 20):
    """Largest U in {4, 2, 1} dividing n_blocks whose unrolled live tile
    intermediates (~tile_bytes each) stay within a VMEM stack budget."""
    for u in (4, 2):
        if n_blocks % u == 0 and u * tile_bytes <= cap:
            return u
    return 1


def _pick_heads(bh, s, d, itemsize, tile_bytes, n_streams=4):
    """bh slices per grid step. At short sequence the grid degenerates into
    thousands of tiny steps whose fixed cost (DMA setup/fences) dominates —
    measured 4.8 ms for a 4096-tile fwd at s2048/d64 where the MXU floor is
    ~2.9 ms. Batching G heads per step amortizes that cost AND hands the
    scheduler G independent tile streams to interleave. G is capped so the
    per-step streams (k/v/q/o per head, double-buffered) and the G live
    tile intermediates stay inside scoped VMEM."""
    for g in (8, 4, 2):
        if bh % g:
            continue
        streams = g * n_streams * s * d * itemsize * 2   # x2 double-buffer
        if streams <= 6 * 2 ** 20 and g * tile_bytes <= 8 * 2 ** 20:
            return g
    return 1


def _flash_fwd_bhsd(q, k, v, *, causal, block_q, block_k, interpret):
    """q/k/v: [BH, S, D] -> (out [BH, S, D], lse [BH, S] f32)."""
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    scale = 1.0 / math.sqrt(d)
    G = _pick_heads(bh, s, d, q.dtype.itemsize, 8 * block_q * block_k)
    # measured d64/s8192: U=2 beats U=1 (~+6%) and U=4 (VMEM pressure)
    unroll = _pick_unroll(s // block_k, G * 8 * block_q * block_k)
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=s, unroll=unroll,
                               heads=G, local_softmax=d >= 128)
    grid = (bh // G, s // block_q)
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, 1, s), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((G, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((G, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((G, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((G, block_q, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((G, 1, block_q), lambda b, i: (b, 0, i))),
        interpret=interpret,
    )(q, k, v)


def _delta(g, o):
    """delta = rowsum(dO * O) as [BH, 1, S] — the softmax-grad correction
    term, computed once in XLA for BOTH backward implementations."""
    return jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                   axis=-1)[:, None, :]


def _bwd_tile_pds(q, k, v, do, lse2, delta, *, scale, masked, q0, k0):
    """Shared per-tile backward math: (p, ds) for a [Bq, D] q/do tile
    against a [Bk, D] k/v tile with global row/col offsets (q0, k0).
    `lse2` is the logsumexp pre-scaled by log2(e) (base-2 softmax domain);
    `masked` is static — callers split their trip counts at the causal
    diagonal so bulk tiles compile without the mask passes.
    Single source of truth for the two-pass AND fused backward kernels —
    their gradients must agree bit-for-bit regardless of which path
    _flash_core_bwd's size guard selects."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) \
        * (scale * _LOG2E)
    if masked:
        qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos <= qpos, s, -1e30)
    p = jnp.exp2(s - lse2)                                      # [Bq, Bk]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = (p * (dp - delta)).astype(q.dtype)
    return p, ds


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                      *, scale, causal, block_k, seq_len, unroll):
    qi = pl.program_id(1)
    q = q_ref[0]                                 # [Bq, D] (native dtype)
    do = do_ref[0]
    lse2 = lse_ref[0, 0][:, None] * _LOG2E       # [Bq, 1] base-2 domain
    delta = delta_ref[0, 0][:, None]             # [Bq, 1]
    block_q = q.shape[0]
    n_kb = seq_len // block_k
    U = unroll

    def body(kb, dq, masked):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        _, ds = _bwd_tile_pds(q, k, v, do, lse2, delta, scale=scale,
                              masked=masked, q0=qi * block_q,
                              k0=kb * block_k)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def group(g, dq, masked):
        for j in range(U):
            dq = body(g * U + j, dq, masked)
        return dq

    dq = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    if causal:
        # overrun tiles past kmax are fully causal-masked: p == 0 -> ds == 0
        n_full = (qi * block_q) // block_k
        kmax = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k, n_kb)
        nf_g = n_full // U
        ng = (kmax + U - 1) // U
        dq = jax.lax.fori_loop(0, nf_g, lambda g, c: group(g, c, False), dq)
        dq = jax.lax.fori_loop(nf_g, ng, lambda g, c: group(g, c, True), dq)
    else:
        dq = jax.lax.fori_loop(0, n_kb // U,
                               lambda g, c: group(g, c, False), dq)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, *, scale, causal, block_q, seq_len,
                       unroll):
    ki = pl.program_id(1)
    k = k_ref[0]                                 # [Bk, D] (native dtype)
    v = v_ref[0]
    block_k = k.shape[0]
    n_qb = seq_len // block_q
    U = unroll

    def body(qb, carry, masked):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse2 = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None] * _LOG2E
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        p, ds = _bwd_tile_pds(q, k, v, do, lse2, delta, scale=scale,
                              masked=masked, q0=qb * block_q,
                              k0=ki * block_k)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    def group(g, carry, masked):
        for j in range(U):
            carry = body(g * U + j, carry, masked)
        return carry

    d = k.shape[1]
    z = jnp.zeros((block_k, d), jnp.float32)
    carry = (z, z)
    if causal:
        # q-block groups strictly before this k block see nothing of it
        # (leading tiles of the first group are above-diagonal: fully
        # masked, contribute zero); groups crossing the diagonal mask;
        # groups fully past it skip the mask.
        qmin = (ki * block_k) // block_q
        qfull = jnp.minimum(
            ((ki + 1) * block_k - 1 + block_q - 1) // block_q, n_qb)
        qmin_g = qmin // U
        qfull_g = (qfull + U - 1) // U
        carry = jax.lax.fori_loop(qmin_g, qfull_g,
                                  lambda g, c: group(g, c, True), carry)
        carry = jax.lax.fori_loop(qfull_g, n_qb // U,
                                  lambda g, c: group(g, c, False), carry)
    else:
        carry = jax.lax.fori_loop(0, n_qb // U,
                                  lambda g, c: group(g, c, False), carry)
    dk, dv = carry
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_bhsd(q, k, v, o, lse, g, *, causal, block_q, block_k,
                    interpret):
    """Backward: returns (dq, dk, dv), each [BH, S, D]."""
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    scale = 1.0 / math.sqrt(d)
    delta = _delta(g, o)                 # [BH, 1, S], matches lse layout

    full = lambda b, i: (b, 0, 0)  # noqa: E731
    # bwd tile live set: s/p/dp f32 + ds bf16 per unrolled tile
    unroll_q = _pick_unroll(s // block_k, 14 * block_q * block_k,
                            cap=8 * 2 ** 20)
    unroll_kv = _pick_unroll(s // block_q, 14 * block_q * block_k,
                             cap=8 * 2 ** 20)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_len=s, unroll=unroll_q),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), full),
            pl.BlockSpec((1, s, d), full),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_len=s, unroll=unroll_kv),
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)),
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, s, d), full),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), full),
            pl.BlockSpec((1, 1, s), full),
            pl.BlockSpec((1, 1, s), full),
        ],
        out_specs=(pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _reference_bhsd(q, k, v, causal):
    """Fused-XLA baseline: native-dtype dots with f32 accumulate/softmax —
    the same MXU precision regime as the Pallas kernel, so speedups compare
    kernel structure, not a dtype handicap on the baseline."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bsd,btd->bst", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        n = s.shape[-1]
        mask = jnp.tril(jnp.ones((s.shape[-2], n), dtype=bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bst,btd->bsd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_bhsd(q, k, v, causal=causal, block_q=block_q,
                             block_k=block_k, interpret=interpret)
    return out


def _flash_core_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_bhsd(q, k, v, causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    bh, s, d = q.shape
    # The fused single-pass backward wins UNDER jax.grad composition at
    # both head dims (measured r5, steps/s under grad at s8192: d64 148
    # fused vs 121 two-pass; d128 279 vs 238 — standalone kernel timings
    # said the opposite, but the grad-composed program schedules the
    # two-pass's three pallas calls worse). Keep the fused default with
    # its VMEM-residency guard; the two-pass covers everything else
    # (tests/test_flash_attention.py asserts grad parity between the two).
    vmem_est = (3 * q.dtype.itemsize + 4) * s * d + 8 * s
    if s % block_q == 0 and s % block_k == 0 \
            and vmem_est < _FUSED_BWD_VMEM_CAP:
        return _flash_bwd_fused_bhsd(q, k, v, o, lse, g, causal=causal,
                                     block_q=block_q, block_k=block_k,
                                     interpret=interpret)
    return _flash_bwd_bhsd(q, k, v, o, lse, g, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# resident streams for the fused backward: q/do/dq at [S, D] + f32 dq
# scratch (k/v/dk/dv stream per k-block); stay inside scoped vmem with
# headroom for fusions jax.grad composes around the custom call.
# 12 MiB admits d128/s8192 (10.5 MiB resident, measured compiling + 0.51
# MFU under grad); d256 long-seq falls to the streaming two-pass.
_FUSED_BWD_VMEM_CAP = 12 * 2 ** 20


def flash_attention_arrays(q, k, v, causal=False, block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K):
    """q/k/v: [B, S, H, D] (paddle layout). Returns [B, S, H, D]."""
    b, s, h, d = q.shape
    if k.shape[1] != s or v.shape[1] != s:
        raise ValueError(
            f"flash_attention requires q/k/v to share seq_len; got q={s}, "
            f"k={k.shape[1]}, v={v.shape[1]} (cross-length attention takes "
            "the fused path)")
    interpret = jax.default_backend() != "tpu"

    # dots require matching operand dtypes (e.g. fp32 KV cache against bf16
    # activations): promote to a common dtype once at the boundary
    ct = jnp.result_type(q.dtype, k.dtype, v.dtype)
    if q.dtype != ct or k.dtype != ct or v.dtype != ct:
        q, k, v = q.astype(ct), k.astype(ct), v.astype(ct)

    def to_bhsd(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)

    bq = min(block_q, max(128, 1 << (s - 1).bit_length()) if s < block_q else block_q)
    bq = min(bq, s)
    bk = min(block_k, s)
    qb, kb_, vb = to_bhsd(q), to_bhsd(k), to_bhsd(v)
    # The kernel grid is s//bq q-blocks x s//bk k-blocks: seq must divide by
    # BOTH chosen blocks or tail rows/keys would be silently dropped. Ragged
    # lengths fall back to the fused XLA reference.
    if s % bq or s % bk:
        out = _reference_bhsd(qb, kb_, vb, causal)
    else:
        out = _flash_core(qb, kb_, vb, causal, bq, bk, interpret)
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)


def flash_attention(q, k, v, causal=False, block_q=DEFAULT_BLOCK_Q,
                    block_k=DEFAULT_BLOCK_K):
    """Tensor-level entry (records one tape node; used by nn attention)."""
    from ..ops._dispatch import ensure_tensor, run_op
    q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
    return run_op(
        lambda a, b, c: flash_attention_arrays(a, b, c, causal=causal,
                                               block_q=block_q, block_k=block_k),
        [q, k, v], "flash_attention")


# ---- ring-attention block kernels ------------------------------------------
# Building blocks for sequence-parallel ring attention (parallel/sp.py):
# each chip's local q attends one rotating K/V shard per ring hop. The
# kernels are the same online-softmax tiles as above, plus a global
# (q_offset, k_offset) pair in SMEM so causal masking and the block trip
# counts see GLOBAL sequence positions — hops that are entirely in the
# masked future run ZERO k-block iterations, which is where causal ring
# attention gets its ~2x FLOP saving over dense sharded attention.
# The lse emitted by the forward is what the ring hop-merge combines
# (out = sum_hops exp(lse_hop - lse_total) * out_hop).

def _fa_ring_fwd_kernel(q_ref, k_ref, v_ref, off_ref, o_ref, lse_ref, *,
                        scale, causal, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    block_q = q.shape[0]
    n_kb = kv_len // block_k
    if causal:
        q_off = off_ref[0]
        k_off = off_ref[1]
        vis = q_off + (qi + 1) * block_q - k_off   # visible keys this q block
        kmax = jnp.clip((vis + block_k - 1) // block_k, 0, n_kb)
    else:
        kmax = n_kb

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = k_off + kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, -1e30)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, kmax, body, (m0, l0, a0))
    lsafe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / lsafe).astype(o_ref.dtype)
    # rows with no visible keys get lse ~ -1e30 -> zero weight in the merge
    lse_ref[0, 0] = jnp.where(l[:, 0] > 0.0, (m + jnp.log(lsafe))[:, 0], -1e30)


def _fa_ring_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       off_ref, dq_ref, *, scale, causal, block_k, kv_len):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    block_q = q.shape[0]
    n_kb = kv_len // block_k
    if causal:
        q_off = off_ref[0]
        k_off = off_ref[1]
        vis = q_off + (qi + 1) * block_q - k_off
        kmax = jnp.clip((vis + block_k - 1) // block_k, 0, n_kb)
    else:
        kmax = n_kb

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = k_off + kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, -1e30)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    dq = jax.lax.fori_loop(0, kmax, body, dq0)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _fa_ring_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        off_ref, dk_ref, dv_ref, *, scale, causal, block_q,
                        q_len):
    ki = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    block_k = k.shape[0]
    n_qb = q_len // block_q
    if causal:
        q_off = off_ref[0]
        k_off = off_ref[1]
        # first q block whose last row reaches this k block's first key
        qmin = jnp.clip((k_off + ki * block_k - q_off) // block_q, 0, n_qb)
    else:
        qmin = 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = k_off + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, -1e30)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    d = k.shape[1]
    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qmin, n_qb, body, (z, z))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def ring_block_fwd(q, k, v, offs, *, causal, block_q, block_k, interpret):
    """One ring hop: local q [BH,Sq,D] x held k/v [BH,Sk,D] ->
    (out [BH,Sq,D], lse [BH,1,Sq] f32). offs = int32[2] global offsets."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    return pl.pallas_call(
        functools.partial(_fa_ring_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, kv_len=sk),
        out_shape=(jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32)),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            _smem_spec(),
        ],
        out_specs=(pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i))),
        interpret=interpret,
    )(q, k, v, offs)


def ring_block_dq(q, k, v, do, lse, delta, offs, *, causal, block_q, block_k,
                  interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    full = lambda b, i: (b, 0, 0)  # noqa: E731
    return pl.pallas_call(
        functools.partial(_fa_ring_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, kv_len=sk),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), full),
            pl.BlockSpec((1, sk, d), full),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            _smem_spec(),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, do, lse, delta, offs)


def ring_block_dkv(q, k, v, do, lse, delta, offs, *, causal, block_q, block_k,
                   interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    full = lambda b, i: (b, 0, 0)  # noqa: E731
    return pl.pallas_call(
        functools.partial(_fa_ring_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, q_len=sq),
        out_shape=(jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, sk, d), jnp.float32)),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), full),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sq, d), full),
            pl.BlockSpec((1, 1, sq), full),
            pl.BlockSpec((1, 1, sq), full),
            _smem_spec(),
        ],
        out_specs=(pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))),
        interpret=interpret,
    )(q, k, v, do, lse, delta, offs)


# ---- fused single-pass backward ---------------------------------------------
# The two-kernel backward computes p = exp(s - lse) and ds TWICE (once for
# dq, once for dk/dv) — 7 tile dots and double the VPU softmax work. This
# kernel makes ONE pass over the (q-block, k-block) tiles computing all
# three grads: 5 dots, p/ds once (delta arrives from a cheap XLA prepass,
# shared with the two-pass path). Grid is (bh, k-blocks) — sequential on
# the TensorCore — with k/v/dk/dv streamed per k-block while q/do stay
# VMEM-resident and dq accumulates in persistent f32 scratch across the
# k-block steps (written out on the last one), keeping the footprint
# inside the 16 MiB scoped-vmem budget with headroom for the fusions
# jax.grad composes around the custom call.

def _fa_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dk_ref, dv_ref, dq_acc, *,
                         scale, causal, block_q, block_k, seq_len):
    ki = pl.program_id(1)
    n_qb = seq_len // block_q
    n_kb = seq_len // block_k

    @pl.when(ki == 0)
    def _init():
        def zstep(qb, _):
            dq_acc[pl.ds(qb * block_q, block_q), :] = jnp.zeros(
                (block_q, q_ref.shape[2]), jnp.float32)
            return 0

        jax.lax.fori_loop(0, n_qb, zstep, 0)

    k = k_ref[0]
    v = v_ref[0]

    def qstep(qb, carry, masked):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse2 = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None] * _LOG2E
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        p, ds = _bwd_tile_pds(q, k, v, do, lse2, delta, scale=scale,
                              masked=masked, q0=qb * block_q,
                              k0=ki * block_k)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        sl = pl.ds(qb * block_q, block_q)
        dq_acc[sl, :] = dq_acc[sl, :] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    d = k.shape[1]
    z = jnp.zeros((block_k, d), jnp.float32)
    carry = (z, z)
    if causal:
        qmin = (ki * block_k) // block_q
        qfull = jnp.minimum(
            ((ki + 1) * block_k - 1 + block_q - 1) // block_q, n_qb)
        carry = jax.lax.fori_loop(qmin, qfull,
                                  lambda qb, c: qstep(qb, c, True), carry)
        carry = jax.lax.fori_loop(qfull, n_qb,
                                  lambda qb, c: qstep(qb, c, False), carry)
    else:
        carry = jax.lax.fori_loop(0, n_qb,
                                  lambda qb, c: qstep(qb, c, False), carry)
    dk, dv = carry
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)

    @pl.when(ki == n_kb - 1)
    def _write_dq():
        def wstep(qb, _):
            sl = pl.ds(qb * block_q, block_q)
            dq_ref[0, sl, :] = (dq_acc[sl, :] * scale).astype(dq_ref.dtype)
            return 0

        jax.lax.fori_loop(0, n_qb, wstep, 0)


def _flash_bwd_fused_bhsd(q, k, v, o, lse, g, *, causal, block_q, block_k,
                          interpret):
    bh, s, d = q.shape
    # the caller guarantees block_q and block_k divide s (the kernel's
    # trip counts bake the divisibility in) — no clamping here
    scale = 1.0 / math.sqrt(d)
    # delta in a cheap XLA prepass (shared with the two-pass path):
    # keeping o resident in the kernel pushed the VMEM footprint past the
    # 16 MiB scoped budget once jax.grad composed copies into it
    delta = _delta(g, o)
    full = lambda b, i: (b, 0, 0)  # noqa: E731
    return pl.pallas_call(
        functools.partial(_fa_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=s),
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s, d), v.dtype)),
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, s, d), full),                      # q
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),   # k
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),   # v
            pl.BlockSpec((1, s, d), full),                      # do
            pl.BlockSpec((1, 1, s), full),                      # lse
            pl.BlockSpec((1, 1, s), full),                      # delta
        ],
        out_specs=(pl.BlockSpec((1, s, d), full),               # dq (last)
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))),
        scratch_shapes=[pltpu.VMEM((s, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
