"""Pallas TPU kernels (the hand-written hot ops; XLA handles the rest)."""
from .flash_attention import flash_attention, flash_attention_arrays  # noqa: F401
