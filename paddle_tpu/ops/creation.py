"""Tensor creation ops.

Reference parity: `python/paddle/tensor/creation.py` (to_tensor, zeros, ones,
full, arange, linspace, eye, tril/triu, assign …).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, Parameter
from ._dispatch import ensure_tensor, run_op, to_arr


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    import jax
    dtype = convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._value
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        return Tensor(arr, stop_gradient=stop_gradient)
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        arr = data if dtype is None or data.dtype == dtype else data.astype(dtype)
        return Tensor(arr, stop_gradient=stop_gradient)
    if dtype is None:
        a = np.asarray(data)
        if a.dtype == np.float64:
            a = a.astype(get_default_dtype())
        arr = jnp.asarray(a)
    else:
        arr = jnp.asarray(np.asarray(data), dtype=dtype)
    return Tensor(arr, stop_gradient=stop_gradient)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor shape -> static ints)
    if isinstance(shape, (int, np.integer)):
        shape = [int(shape)]
    return [int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), dtype=convert_dtype(dtype) or get_default_dtype()))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), dtype=convert_dtype(dtype) or get_default_dtype()))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = to_arr(fill_value)
    dt = convert_dtype(dtype)
    if dt is None:
        dt = jnp.asarray(fill_value).dtype
        if dt == jnp.float64:
            dt = get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, dtype=dt))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(to_arr(x), dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(to_arr(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(to_arr(x), to_arr(fill_value), dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    start, end, step = to_arr(start), to_arr(end), to_arr(step)
    dt = convert_dtype(dtype)
    if dt is None:
        py = (start, end, step)
        dt = np.dtype("float32") if any(isinstance(v, float) for v in py) else np.dtype("int64")
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(to_arr(start), to_arr(stop), int(num),
                               dtype=convert_dtype(dtype) or get_default_dtype()))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns),
                          dtype=convert_dtype(dtype) or get_default_dtype()))


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)
    if padding_value != 0 and x.ndim == 1:
        def f(a):
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(d.shape[0], dtype=bool) if offset == 0 else \
                jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
            return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
        return run_op(f, [x], "diag")
    return run_op(lambda a: jnp.diag(a, k=offset), [x], "diag")


def diagflat(x, offset=0, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.diagflat(a, k=offset), [x], "diagflat")


def tril(x, diagonal=0, name=None):
    return run_op(lambda a: jnp.tril(a, k=diagonal), [ensure_tensor(x)], "tril")


def triu(x, diagonal=0, name=None):
    return run_op(lambda a: jnp.triu(a, k=diagonal), [ensure_tensor(x)], "triu")


def meshgrid(*args, **kwargs):
    ts = [ensure_tensor(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[t._value for t in ts], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    x = ensure_tensor(x)
    out = run_op(lambda a: a + 0, [x], "assign")
    if output is not None:
        output._value = out._value
        output._node = out._node
        if out._node is not None:
            out._node.outputs = [output if o is out else o for o in out._node.outputs]
            output.stop_gradient = False
        return output
    return out


def clone(x, name=None):
    return assign(x)


def numel(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size, dtype=jnp.int32))


def create_parameter(shape, dtype=None, name=None, default_initializer=None, attr=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    if default_initializer is None:
        arr = jnp.zeros(_shape_list(shape), dtype=dt)
        p = Parameter(arr, name=name)
    else:
        p = Parameter(jnp.zeros(_shape_list(shape), dtype=dt), name=name)
        default_initializer(p)
    return p


def clip_by_norm(x, max_norm, name=None):
    x = ensure_tensor(x)

    def f(a):
        n = jnp.sqrt(jnp.sum(a * a))
        return jnp.where(n > max_norm, a * (max_norm / jnp.maximum(n, 1e-12)), a)

    return run_op(f, [x], "clip_by_norm")


def complex(real, imag, name=None):
    """Build a complex tensor from real + imaginary parts
    (`python/paddle/tensor/creation.py` complex)."""
    import jax as _jax
    real, imag = ensure_tensor(real), ensure_tensor(imag)
    return run_op(lambda r, i: _jax.lax.complex(r, i), [real, imag], "complex")


def is_complex(x):
    import jax.numpy as jnp
    x = ensure_tensor(x)
    return jnp.issubdtype(x._value.dtype, jnp.complexfloating)


def is_floating_point(x):
    import jax.numpy as jnp
    x = ensure_tensor(x)
    return jnp.issubdtype(x._value.dtype, jnp.floating)


def is_integer(x):
    import jax.numpy as jnp
    x = ensure_tensor(x)
    return jnp.issubdtype(x._value.dtype, jnp.integer)
