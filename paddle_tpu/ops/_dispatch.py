"""Op dispatch: Tensor-aware wrappers over pure JAX functions.

Reference parity: this replaces the phi KernelFactory/KernelKey dispatch
(`phi/core/kernel_factory.h:50`) + generated `core.ops.*` bindings
(`pybind/op_function_generator.cc:388`). On TPU there is one backend — XLA —
so "kernel selection" degenerates to tracing a jax function; JAX's own
per-primitive executable cache plays the role of the fluid op kernel cache.
Autograd recording (tape + VJP) happens here, mirroring Tracer::TraceOp.
"""
from __future__ import annotations

import time as _time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

_TracerTypes = (jax.core.Tracer,)

from .. import monitor as _monitor
from ..core import autograd
from ..core import flags as _flags
from ..core.tensor import Tensor
from . import lazy as _lazy

__all__ = ["run_op", "unary_op", "binary_op", "to_arr", "ensure_tensor", "inplace_from"]


def _check_nan_inf(name: str, outs) -> None:
    """FLAGS_check_nan_inf parity (`operator.cc:1171` ->
    `details/nan_inf_utils_detail.cc:314`): scan op outputs, abort on bad
    values. Debug-only path — it host-syncs every op, exactly like the
    reference's device-wide scan."""
    seq = outs if isinstance(outs, tuple) else (outs,)
    for i, o in enumerate(seq):
        if isinstance(o, jnp.ndarray) and jnp.issubdtype(o.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(o))):
                raise FloatingPointError(
                    f"Operator {name} output {i} contains NaN/Inf "
                    "(FLAGS_check_nan_inf=True)")


def to_arr(x):
    return x._value if isinstance(x, Tensor) else x


def ensure_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    arr = jnp.asarray(x, dtype=dtype)
    return Tensor(arr)


# set by paddle_tpu.profiler.Profiler.start(): fn(name, t0, t1) or None
_PROFILE_HOOK = None


def run_op(fn: Callable, tensors: Sequence[Tensor], name: str = "op"):
    """Execute fn over the arrays of `tensors`; record a tape node if needed.

    fn must be a pure function of the positional arrays only (close over any
    static attrs). Returns Tensor or tuple[Tensor].

    Instrumentation: with neither a profiler hook nor FLAGS_monitor active,
    the fast path below is two attribute checks and a tail call — no timer,
    no try frame, no hook installation. FLAGS_lazy_eager adds exactly one
    more module-attribute check; when active, the op is DEFERRED into the
    per-thread segment (ops/lazy.py) unless it must fall back to immediate
    dispatch (tracer inputs, unkeyable closure, untraceable shapes).
    """
    if _lazy._ACTIVE:
        r = _lazy.defer_op(fn, tensors, name)
        if r is not _lazy._FALLBACK:
            return r
    if _PROFILE_HOOK is None and not _monitor._ENABLED:
        return _run_op_impl(fn, tensors, name)
    _t0 = _time.time()
    try:
        return _run_op_impl(fn, tensors, name)
    finally:
        _t1 = _time.time()
        if _PROFILE_HOOK is not None:
            _PROFILE_HOOK(name, _t0, _t1)
        if _monitor._ENABLED:
            _monitor.record_op(name, _t1 - _t0)


def _run_op_impl(fn: Callable, tensors: Sequence[Tensor], name: str = "op"):
    outs, vjp = autograd.apply_op(fn, tensors, name=name)
    if _flags.flag("check_nan_inf") and not isinstance(
            outs[0] if isinstance(outs, tuple) else outs, _TracerTypes):
        _check_nan_inf(name, outs)
    if isinstance(outs, tuple):
        wrapped = tuple(Tensor(o) for o in outs)
        if vjp is not None:
            autograd.record_node(vjp, tensors, list(wrapped), name, fn=fn)
        return wrapped
    out = Tensor(outs)
    if vjp is not None:
        autograd.record_node(vjp, tensors, [out], name, fn=fn)
    return out


def nondiff_op(fn: Callable, tensors: Sequence[Tensor]):
    """Run with no tape recording (integer/boolean outputs)."""
    if _lazy._ACTIVE:
        r = _lazy.defer_nondiff(fn, tensors)
        if r is not _lazy._FALLBACK:
            return r
    arrs = tuple(t._value for t in tensors)
    outs = fn(*arrs)
    if isinstance(outs, tuple):
        return tuple(Tensor(o) for o in outs)
    return Tensor(outs)


def unary_op(jfn: Callable, name: str):
    def op(x, name_=None, **kw):
        x = ensure_tensor(x)
        if kw:
            return run_op(lambda a: jfn(a, **kw), [x], name)
        return run_op(jfn, [x], name)

    op.__name__ = name
    return op


def binary_op(jfn: Callable, name: str):
    def op(x, y, name_=None):
        tx, ty = isinstance(x, Tensor), isinstance(y, Tensor)
        if tx and ty:
            return run_op(jfn, [x, y], name)
        if tx:
            yv = y
            return run_op(lambda a: jfn(a, yv), [x], name)
        if ty:
            xv = x
            return run_op(lambda b: jfn(xv, b), [y], name)
        return Tensor(jfn(jnp.asarray(x), jnp.asarray(y)))

    op.__name__ = name
    return op


def inplace_from(x: Tensor, result: Tensor) -> Tensor:
    """Rebind x's payload to result's, transferring the tape node so backward
    through later consumers of x routes correctly (inplace `op_` variants).

    When the recorded node consumed x itself, snapshot the pre-modification
    tensor into a fresh object so the producer chain of the old value stays
    reachable (no self-loop on the tape)."""
    node = result._node
    if node is not None and any(t is x for t in node.inputs):
        old = Tensor(x._value, stop_gradient=x.stop_gradient)
        old._node = x._node
        if old._node is not None:
            old._node.outputs = [old if o is x else o for o in old._node.outputs]
        node.inputs = [old if t is x else t for t in node.inputs]
    x._value = result._value
    if type(x._value) is _lazy._LazyValue:
        # deferred result (FLAGS_lazy_eager): register the alias so the
        # flush rebinds x to the concrete array too
        x._value._ts.append(x)
    if node is not None:
        node.outputs = [x if o is result else o for o in node.outputs]
        x._node = node
        x.stop_gradient = False
    return x
