"""Comparison / logical / bitwise ops.

Reference parity: `python/paddle/tensor/logic.py` + `operators/controlflow/`
logical ops. All outputs are non-differentiable (never recorded on the tape).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ._dispatch import ensure_tensor, nondiff_op, to_arr


def _cmp(jfn, name):
    def op(x, y, name_=None):
        xv, yv = to_arr(x), to_arr(y)
        return Tensor(jfn(jnp.asarray(xv), jnp.asarray(yv)))

    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")

logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")

bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, out=None, name=None):
    return Tensor(jnp.logical_not(to_arr(x)))


def bitwise_not(x, out=None, name=None):
    return Tensor(jnp.bitwise_not(to_arr(x)))


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(to_arr(x), to_arr(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(to_arr(x), to_arr(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(to_arr(x), to_arr(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return nondiff_op(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), [ensure_tensor(x)])


def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return nondiff_op(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), [ensure_tensor(x)])


def is_empty(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def in_dynamic_mode():
    return True


bitwise_left_shift = _cmp(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _cmp(jnp.right_shift, "bitwise_right_shift")
