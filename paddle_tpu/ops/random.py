"""Random sampling ops.

Reference parity: `python/paddle/tensor/random.py` (uniform, normal, randint,
randperm, bernoulli, multinomial, …) over the phi RNG kernels. TPU-first:
eager calls draw fresh keys from the global `Generator`
(`paddle_tpu.core.random`); inside jitted/static programs use
`paddle_tpu.jit`'s key plumbing instead of these stateful entry points.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor
from ._dispatch import ensure_tensor, to_arr

__all__ = [
    "uniform", "uniform_", "normal", "gaussian", "standard_normal", "randn", "rand",
    "randint", "randint_like", "randperm", "bernoulli", "multinomial", "poisson",
    "exponential_", "shuffle",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor shape -> static ints)
    if isinstance(shape, (int, np.integer)):
        shape = [int(shape)]
    return [int(s) for s in shape]


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    key = rnd.next_key() if seed == 0 else jax.random.key(seed)
    return Tensor(jax.random.uniform(key, _shape_list(shape), dtype=dt,
                                     minval=to_arr(min), maxval=to_arr(max)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x = ensure_tensor(x)
    x._value = jax.random.uniform(rnd.next_key(), tuple(x.shape), dtype=x._value.dtype,
                                  minval=min, maxval=max)
    return x


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    key = rnd.next_key()
    return Tensor(jax.random.normal(key, _shape_list(shape), dtype=dt) * std + mean)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = jnp.asarray(to_arr(mean)), jnp.asarray(to_arr(std))
        shp = jnp.broadcast_shapes(m.shape, s.shape)
        key = rnd.next_key()
        return Tensor(jax.random.normal(key, shp, dtype=m.dtype if m.dtype != jnp.int32 else jnp.float32) * s + m)
    return gaussian(shape if shape is not None else [1], mean, std)


def standard_normal(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dt = convert_dtype(dtype)
    key = rnd.next_key()
    return Tensor(jax.random.randint(key, _shape_list(shape), int(low), int(high),
                                     dtype=dt if np.issubdtype(dt, np.integer) else jnp.int32))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, tuple(x.shape), dtype or "int32")


def randperm(n, dtype="int64", name=None):
    key = rnd.next_key()
    return Tensor(jax.random.permutation(key, int(n)).astype(convert_dtype(dtype) if
                                                             np.issubdtype(convert_dtype(dtype), np.integer) else jnp.int32))


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    key = rnd.next_key()
    return Tensor(jax.random.bernoulli(key, x._value).astype(x._value.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    key = rnd.next_key()
    p = x._value / jnp.sum(x._value, axis=-1, keepdims=True)
    if x.ndim == 1:
        out = jax.random.choice(key, x.shape[0], shape=(num_samples,), replace=replacement, p=p)
    else:
        keys = jax.random.split(key, x.shape[0])
        out = jnp.stack([
            jax.random.choice(keys[i], x.shape[-1], shape=(num_samples,),
                              replace=replacement, p=p[i])
            for i in range(x.shape[0])])
    return Tensor(out)


def poisson(x, name=None):
    x = ensure_tensor(x)
    key = rnd.next_key()
    return Tensor(jax.random.poisson(key, x._value).astype(x._value.dtype))


def exponential_(x, lam=1.0, name=None):
    x = ensure_tensor(x)
    key = rnd.next_key()
    x._value = (jax.random.exponential(key, tuple(x.shape), dtype=x._value.dtype) / lam)
    return x


def shuffle(x, axis=0):
    x = ensure_tensor(x)
    key = rnd.next_key()
    return Tensor(jax.random.permutation(key, x._value, axis=axis))
