"""Shape/layout manipulation ops.

Reference parity: `python/paddle/tensor/manipulation.py` (reshape, transpose,
concat, split, gather, scatter, tile, expand, pad, …) over the fluid op corpus.
All are XLA-friendly: static shapes, no data-dependent output sizes except
where noted (masked_select/nonzero are host-synced, as on any accelerator).
"""
from __future__ import annotations

import builtins
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ._dispatch import ensure_tensor, inplace_from, nondiff_op, run_op, to_arr


def _norm_shape(shape, cur_shape):
    """Paddle reshape semantics: -1 infers, 0 copies the input dim."""
    if isinstance(shape, Tensor):
        shape = shape.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor shape -> static ints)
    shape = [int(s) for s in shape]
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(cur_shape[i])
        else:
            out.append(s)
    return out


def cast(x, dtype):
    x = ensure_tensor(x)
    dt = convert_dtype(dtype)
    from ..core.dtype import is_floating
    if is_floating(x.dtype) and is_floating(dt):
        return run_op(lambda a: a.astype(dt), [x], "cast")
    return nondiff_op(lambda a: a.astype(dt), [x])


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    ns = _norm_shape(shape, x.shape)
    return run_op(lambda a: a.reshape(ns), [x], "reshape")


def reshape_(x, shape, name=None):
    return inplace_from(x, reshape(x, shape))


def transpose(x, perm, name=None):
    x = ensure_tensor(x)
    perm = [int(p) for p in perm]
    return run_op(lambda a: jnp.transpose(a, perm), [x], "transpose")


def swapaxes(x, axis0, axis1, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.swapaxes(a, axis0, axis1), [x], "swapaxes")


moveaxis = lambda x, source, destination, name=None: run_op(
    lambda a: jnp.moveaxis(a, source, destination), [ensure_tensor(x)], "moveaxis")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    so = stop_axis % nd if nd else 0
    shp = x.shape
    new = shp[:sa] + [int(np.prod(shp[sa:so + 1])) if shp[sa:so + 1] else 1] + shp[so + 1:]
    return run_op(lambda a: a.reshape(new), [x], "flatten")


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    elif isinstance(axis, (list, tuple)):
        ax = tuple(int(a) for a in axis if x.shape[int(a)] == 1)
    else:
        ax = int(axis)
        if x.shape[ax] != 1:
            return run_op(lambda a: a, [x], "squeeze")
    return run_op(lambda a: jnp.squeeze(a, axis=ax), [x], "squeeze")


def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    ax = tuple(int(a) for a in axis) if isinstance(axis, (list, tuple)) else int(axis)
    return run_op(lambda a: jnp.expand_dims(a, ax), [x], "unsqueeze")


squeeze_ = lambda x, axis=None, name=None: inplace_from(x, squeeze(x, axis))
unsqueeze_ = lambda x, axis=None, name=None: inplace_from(x, unsqueeze(x, axis))


def concat(x, axis=0, name=None):
    ts = [ensure_tensor(t) for t in x]
    ax = int(to_arr(axis)) if isinstance(axis, Tensor) else int(axis)
    return run_op(lambda *arrs: jnp.concatenate(arrs, axis=ax), ts, "concat")


def stack(x, axis=0, name=None):
    ts = [ensure_tensor(t) for t in x]
    return run_op(lambda *arrs: jnp.stack(arrs, axis=int(axis)), ts, "stack")


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    ax = int(to_arr(axis)) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_unknown = builtins.sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = builtins.sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offs = np.cumsum([0] + sizes)

    def f(a):
        return tuple(jax.lax.slice_in_dim(a, int(offs[i]), int(offs[i + 1]), axis=ax)
                     for i in range(len(sizes)))

    return list(run_op(f, [x], "split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = ensure_tensor(x)
    n = x.shape[int(axis)]
    outs = split(x, n, axis)
    return [squeeze(o, axis=int(axis)) for o in outs]


def slice(x, axes, starts, ends, name=None):
    x = ensure_tensor(x)
    axes = [int(a) for a in axes]
    starts = [int(to_arr(s)) for s in (starts.tolist() if isinstance(starts, Tensor) else starts)]  # tpu-lint: disable=host-sync (paddle API: Tensor starts -> static ints)
    ends = [int(to_arr(e)) for e in (ends.tolist() if isinstance(ends, Tensor) else ends)]  # tpu-lint: disable=host-sync (paddle API: Tensor ends -> static ints)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]

    return run_op(f, [x], "slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[int(ax)] = builtins.slice(int(s), int(e), int(st))
        return a[tuple(idx)]

    return run_op(f, [x], "strided_slice")


def gather(x, index, axis=0, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    ax = int(to_arr(axis)) if isinstance(axis, Tensor) else int(axis)
    return run_op(lambda a: jnp.take(a, index._value.astype(jnp.int32), axis=ax), [x], "gather")


def gather_nd(x, index, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    ind = index._value.astype(jnp.int32)

    def f(a):
        k = ind.shape[-1]
        return a[tuple(jnp.moveaxis(ind, -1, 0)[i] for i in range(k))]

    return run_op(f, [x], "gather_nd")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    ind = indices._value.astype(jnp.int32)
    return run_op(lambda a: jnp.take_along_axis(a, ind, axis=int(axis)), [arr], "take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr = ensure_tensor(arr)
    ind = ensure_tensor(indices)._value.astype(jnp.int32)
    vt = isinstance(values, Tensor)
    vv = values if vt else None

    def f(a, *rest):
        v = rest[0] if rest else jnp.asarray(values, a.dtype)
        v = jnp.broadcast_to(v, ind.shape).astype(a.dtype)
        dims = list(range(a.ndim))
        ax = int(axis) % a.ndim
        idx_grids = jnp.meshgrid(*[jnp.arange(s) for s in ind.shape], indexing="ij")
        full_idx = tuple(ind if d == ax else idx_grids[d] for d in dims)
        if reduce == "assign":
            return a.at[full_idx].set(v)
        if reduce in ("add", "sum"):
            return a.at[full_idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[full_idx].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")

    ins = [arr, vv] if vt else [arr]
    return run_op(f, ins, "put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    x, updates = ensure_tensor(x), ensure_tensor(updates)
    ind = ensure_tensor(index)._value.astype(jnp.int32)

    def f(a, u):
        if overwrite:
            return a.at[ind].set(u.astype(a.dtype))
        return a.at[ind].add(u.astype(a.dtype))

    return run_op(f, [x, updates], "scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    return inplace_from(x, scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    x, updates = ensure_tensor(x), ensure_tensor(updates)
    ind = ensure_tensor(index)._value.astype(jnp.int32)

    def f(a, u):
        k = ind.shape[-1]
        idx = tuple(jnp.moveaxis(ind, -1, 0)[i] for i in range(k))
        return a.at[idx].add(u.astype(a.dtype))

    return run_op(f, [x, updates], "scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=ensure_tensor(updates).dtype)
    return scatter_nd_add(z, index, updates)


def tile(x, repeat_times, name=None):
    x = ensure_tensor(x)
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor repeats -> static ints)
    reps = [int(r) for r in repeat_times]
    return run_op(lambda a: jnp.tile(a, reps), [x], "tile")


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor shape -> static ints)
    tgt = []
    shape = [int(s) for s in shape]
    xs = [1] * (len(shape) - x.ndim) + x.shape
    for s, xd in zip(shape, xs):
        tgt.append(xd if s == -1 else s)
    return run_op(lambda a: jnp.broadcast_to(a, tgt), [x], "expand")


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [expand(t, list(shape)) for t in ts]


def flip(x, axis, name=None):
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return run_op(lambda a: jnp.flip(a, axis=ax), [x], "flip")


def roll(x, shifts, axis=None, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.roll(a, shifts, axis=axis), [x], "roll")


def rot90(x, k=1, axes=(0, 1), name=None):
    return run_op(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [ensure_tensor(x)], "rot90")


def where(condition, x=None, y=None, name=None):
    cond = ensure_tensor(condition)
    if x is None and y is None:
        from .search import nonzero
        return nonzero(cond, as_tuple=True)
    tx, ty = isinstance(x, Tensor), isinstance(y, Tensor)
    c = cond._value.astype(bool)
    if tx and ty:
        return run_op(lambda a, b: jnp.where(c, a, b), [x, y], "where")
    if tx:
        return run_op(lambda a: jnp.where(c, a, y), [x], "where")
    if ty:
        return run_op(lambda b: jnp.where(c, x, b), [y], "where")
    return Tensor(jnp.where(c, x, y))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor pad -> static ints)
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pad applies to the last len(pad)//2 spatial dims,
        # ordered (left, right, top, bottom, ...) innermost-first
        n = len(pad) // 2
        spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(n)][::-1]  # innermost-first
        if data_format.upper().endswith("C"):  # NHWC/NLC/NDHWC: channel last
            widths = [(0, 0)] * (nd - n - 1) + spatial + [(0, 0)]
        else:  # NCHW/NCL/NCDHW
            widths = [(0, 0)] * (nd - n) + spatial
    mode_map = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                "circular": "wrap"}
    jmode = mode_map[mode]

    def f(a):
        if jmode == "constant":
            return jnp.pad(a, widths, mode="constant", constant_values=value)
        return jnp.pad(a, widths, mode=jmode)

    return run_op(f, [x], "pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    reps = to_arr(repeats)
    return run_op(lambda a: jnp.repeat(a, reps, axis=axis), [x], "repeat_interleave")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    a = ensure_tensor(x).numpy()  # host-synced, like any dynamic-shape op on TPU
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    a = ensure_tensor(x).numpy()
    vals = []
    prev = object()
    for v in a.reshape(-1) if axis is None else a:
        if not np.array_equal(v, prev):
            vals.append(v)
        prev = v
    return Tensor(jnp.asarray(np.array(vals)))


def as_complex(x, name=None):
    return run_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [ensure_tensor(x)], "as_complex")


def as_real(x, name=None):
    return run_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                  [ensure_tensor(x)], "as_real")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    input = ensure_tensor(input)
    size = index_num // nshards

    def f(a):
        shard = a // size
        return jnp.where(shard == shard_id, a % size, ignore_value)

    return nondiff_op(f, [input])


# ---- indexing (Tensor __getitem__ / __setitem__) ----
def _conv_idx(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_conv_idx(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


def getitem(x, idx):
    x = ensure_tensor(x)
    jidx = _conv_idx(idx)
    # boolean-mask indexing produces dynamic shape -> host sync (documented)
    if isinstance(jidx, (jax.Array, np.ndarray)) and np.asarray(jidx).dtype == np.bool_:
        mask = np.asarray(jidx)
        sel = np.nonzero(mask.reshape(-1))[0]
        flatn = int(np.prod(x.shape[:mask.ndim]))
        def f(a):
            lead = a.reshape((flatn,) + a.shape[mask.ndim:])
            return jnp.take(lead, jnp.asarray(sel), axis=0)
        return run_op(f, [x], "getitem_mask")
    return run_op(lambda a: a[jidx], [x], "getitem")


def setitem_(x, idx, value):
    x = ensure_tensor(x)
    jidx = _conv_idx(idx)
    if isinstance(value, Tensor):
        out = run_op(lambda a, v: a.at[jidx].set(v.astype(a.dtype)), [x, value], "setitem")
    else:
        out = run_op(lambda a: a.at[jidx].set(jnp.asarray(value, a.dtype)), [x], "setitem")
    return inplace_from(x, out)


def index_put(x, indices, value, accumulate=False, name=None):
    x = ensure_tensor(x)
    idx = tuple(_conv_idx(i) for i in indices)
    v = ensure_tensor(value)

    def f(a, u):
        return a.at[idx].add(u.astype(a.dtype)) if accumulate else a.at[idx].set(u.astype(a.dtype))

    return run_op(f, [x, v], "index_put")


def masked_fill(x, mask, value, name=None):
    x = ensure_tensor(x)
    m = ensure_tensor(mask)._value.astype(bool)
    if isinstance(value, Tensor):
        return run_op(lambda a, v: jnp.where(m, v.astype(a.dtype), a), [x, value], "masked_fill")
    return run_op(lambda a: jnp.where(m, jnp.asarray(value, a.dtype), a), [x], "masked_fill")


def fill_(x, value):
    x = ensure_tensor(x)
    x._value = jnp.full_like(x._value, value)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    x = ensure_tensor(x)
    n = builtins.min(x.shape[-2], x.shape[-1])
    i = jnp.arange(n)
    x._value = x._value.at[..., i, i].set(value)
    return x


# ---- breadth batch (round 2) ----
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.diagonal(a, offset, axis1, axis2),
                  [x], "diagonal")


def take(x, index, mode="raise", name=None):
    """Flat-index gather (tensor/manipulation.py take): x treated 1-D.
    mode='raise' validates on host in eager mode (XLA can't raise
    data-dependently; under a trace it degrades to 'clip')."""
    x = ensure_tensor(x)
    idx = to_arr(ensure_tensor(index)).astype(jnp.int32)
    n = int(np.prod(x.shape)) if x.shape else 1
    if mode == "raise" and not isinstance(idx, jax.core.Tracer):
        iv = np.asarray(idx)
        if iv.size and (iv.min() < -n or iv.max() >= n):
            raise IndexError(
                f"take: index out of range for {n} elements "
                f"(min {iv.min()}, max {iv.max()})")
    jmode = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    if mode != "wrap":
        # python-style negative indexing (paddle take contract); under
        # 'clip' the clamp applies AFTER normalization
        idx = jnp.where(idx < 0, idx + n, idx)
    return run_op(lambda a: jnp.take(a.reshape(-1), idx, mode=jmode),
                  [x], "take")


def unstack(x, axis=0, num=None, name=None):
    x = ensure_tensor(x)
    n = num if num is not None else x.shape[axis]
    outs = run_op(lambda a: tuple(jnp.squeeze(s, axis) for s in
                                  jnp.split(a, n, axis)), [x], "unstack")
    return list(outs)


def crop(x, shape=None, offsets=None, name=None):
    """Slice a sub-box: out[i] = x[offsets[i] : offsets[i]+shape[i]]
    (`python/paddle/tensor/manipulation.py` crop / crop_tensor op).
    shape entries of -1 keep the rest of that dim; offsets default 0."""
    x = ensure_tensor(x)
    get = lambda v: [int(i) for i in (v.numpy().reshape(-1) if hasattr(v, "numpy")  # tpu-lint: disable=host-sync (paddle API: Tensor box -> static ints)
                                      else v)]  # noqa: E731
    shp = get(shape) if shape is not None else list(x.shape)
    offs = get(offsets) if offsets is not None else [0] * len(shp)

    def f(a):
        import builtins
        sl = tuple(builtins.slice(o, a.shape[i] if s == -1 else o + s)
                   for i, (o, s) in enumerate(zip(offs, shp)))
        return a[sl]

    return run_op(f, [x], "crop")


def reverse(x, axis, name=None):
    """Flip along axes (fluid reverse op; alias surface of flip)."""
    return flip(x, axis)


def shape(input):
    """Runtime shape as an int32 tensor (`paddle.shape` / shape op)."""
    from ._dispatch import nondiff_op
    input = ensure_tensor(input)
    return nondiff_op(lambda a: jnp.asarray(a.shape, jnp.int32), [input])


def tolist(x):
    """Nested python list of the tensor's values (utility in
    `python/paddle/tensor/to_string.py` family)."""
    return ensure_tensor(x).tolist()
