"""Sequence ops over (data, lengths) ragged batches.

Reference parity: `paddle/fluid/operators/sequence_ops/` —
sequence_pad/unpad, sequence_mask, sequence_pool (sum/mean/max/first/last),
sequence_expand, sequence_softmax. The reference walks LoD offsets with
per-sequence loops; here every op is a masked dense jnp program (one XLA
fusion, no per-sequence host loop — the TPU hot-path answer).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._dispatch import ensure_tensor, run_op, to_arr

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
           "sequence_expand", "sequence_softmax"]


def sequence_mask(lengths, maxlen: Optional[int] = None, dtype="float32"):
    """[B] -> [B, T] validity mask (sequence_mask_op.cc)."""
    lengths = ensure_tensor(lengths)

    def fn(l):
        t = maxlen if maxlen is not None else int(jnp.max(l))  # eager only
        return (jnp.arange(t)[None, :] < l[:, None]).astype(dtype)

    if maxlen is None:
        # data-dependent output shape: resolve eagerly (host), like the
        # reference's runtime InferShape on LoD
        l = to_arr(lengths)
        t = int(np.asarray(jnp.max(l)))
        return Tensor((jnp.arange(t)[None, :] < l[:, None]).astype(dtype))
    return run_op(fn, [lengths], "sequence_mask")


def sequence_pad(seqs, pad_value=0.0, maxlen: Optional[int] = None):
    """list-of-arrays -> (padded [B,T,...] Tensor, lengths Tensor)
    (sequence_pad_op.cc; host-side assembly, device-side result)."""
    from ..core.lod import DEFAULT_BUCKETS, create_lod_tensor
    if maxlen is not None:
        longest = max(len(s) for s in seqs)
        if longest > maxlen:
            raise ValueError(
                f"sequence_pad: maxlen={maxlen} < longest sequence "
                f"({longest}) — reference sequence_pad_op rejects this")
        buckets = (maxlen,)
    else:
        buckets = DEFAULT_BUCKETS
    lt = create_lod_tensor(seqs, buckets=buckets, pad_value=pad_value)
    return Tensor(lt.data), Tensor(lt.lengths)


def sequence_unpad(x, lengths):
    """Padded [B,T,...] -> list of numpy arrays (sequence_unpad_op.cc)."""
    xv, lv = np.asarray(to_arr(ensure_tensor(x))), np.asarray(to_arr(ensure_tensor(lengths)))
    return [xv[i, :int(l)] for i, l in enumerate(lv)]


def sequence_pool(x, lengths, pool_type: str = "sum"):
    """Masked pooling over T: sum/mean/max/sqrt/first/last
    (sequence_pool_op.cc semantics on the padded layout)."""
    x, lengths = ensure_tensor(x), ensure_tensor(lengths)
    pt = pool_type.lower()

    def fn(v, l):
        t = v.shape[1]
        m = (jnp.arange(t)[None, :] < l[:, None])
        shape = m.shape + (1,) * (v.ndim - 2)
        mf = m.reshape(shape)
        if pt == "sum":
            return jnp.sum(v * mf, axis=1)
        if pt == "average" or pt == "mean":
            return jnp.sum(v * mf, axis=1) / jnp.maximum(
                l.reshape((-1,) + (1,) * (v.ndim - 2)), 1)
        if pt == "sqrt":
            return jnp.sum(v * mf, axis=1) / jnp.sqrt(jnp.maximum(
                l.reshape((-1,) + (1,) * (v.ndim - 2)), 1).astype(v.dtype))
        if pt == "max":
            neg = jnp.where(mf, v, jnp.full_like(v, -jnp.inf))
            return jnp.max(neg, axis=1)
        if pt == "first":
            return v[:, 0]
        if pt == "last":
            idx = jnp.maximum(l - 1, 0)
            return jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), axis=1
            ).squeeze(1)
        raise ValueError(f"unknown pool_type {pool_type!r}")

    return run_op(fn, [x, lengths], f"sequence_pool_{pt}")


def sequence_expand(x, lengths):
    """Repeat row i of x lengths[i] times -> [sum(lengths), ...]
    (sequence_expand_op.cc). Output shape is data-dependent: computed with
    a host-resolved total (padded to the exact sum)."""
    lv = np.asarray(to_arr(ensure_tensor(lengths)))
    reps = jnp.asarray(np.repeat(np.arange(len(lv)), lv))
    return run_op(lambda v: jnp.take(v, reps, axis=0), [ensure_tensor(x)],
                  "sequence_expand")


def sequence_softmax(x, lengths):
    """Masked softmax over T (sequence_softmax_op.cc): padding positions
    get zero probability and contribute nothing to the normalizer."""
    x, lengths = ensure_tensor(x), ensure_tensor(lengths)

    def fn(v, l):
        t = v.shape[1]
        m = jnp.arange(t)[None, :] < l[:, None]
        z = jnp.where(m, v, jnp.full_like(v, -jnp.inf))
        z = z - jax_stop_max(z)
        e = jnp.where(m, jnp.exp(z), jnp.zeros_like(v))
        return e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-30)

    return run_op(fn, [x, lengths], "sequence_softmax")


def jax_stop_max(z):
    import jax
    return jax.lax.stop_gradient(jnp.max(jnp.where(jnp.isfinite(z), z, -1e30),
                                         axis=1, keepdims=True))
