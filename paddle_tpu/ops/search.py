"""Search / sort / sampling-index ops.

Reference parity: `python/paddle/tensor/search.py` (argmax, argsort, topk,
where/nonzero, masked_select, searchsorted, index_sample).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ._dispatch import ensure_tensor, nondiff_op, run_op


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    return nondiff_op(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim), [x])


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    return nondiff_op(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim), [x])


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    x = ensure_tensor(x)

    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=stable, descending=descending)
        return idx

    return nondiff_op(f, [x])


def sort(x, axis=-1, descending=False, stable=True, name=None):
    x = ensure_tensor(x)

    def f(a):
        s = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return s

    return run_op(f, [x], "sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    k = int(k)
    ax = int(axis)

    def fval(a):
        b = jnp.moveaxis(a, ax, -1)
        src = b if largest else -b
        v = jax.lax.top_k(src, k)[0]
        v = v if largest else -v
        return jnp.moveaxis(v, -1, ax)

    def find(a):
        b = jnp.moveaxis(a, ax, -1)
        src = b if largest else -b
        i = jax.lax.top_k(src, k)[1]
        return jnp.moveaxis(i, -1, ax)

    vals = run_op(fval, [x], "topk")
    inds = nondiff_op(find, [x])
    return vals, inds


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = int(axis)

    def f(a):
        s = jnp.sort(a, axis=ax)
        v = jnp.take(s, k - 1, axis=ax)
        return jnp.expand_dims(v, ax) if keepdim else v

    vals = run_op(f, [x], "kthvalue")
    inds = nondiff_op(lambda a: jnp.take(jnp.argsort(a, axis=ax), k - 1, axis=ax), [x])
    if keepdim:
        inds = Tensor(jnp.expand_dims(inds._value, ax))
    return vals, inds


def mode(x, axis=-1, keepdim=False, name=None):
    a = ensure_tensor(x).numpy()
    from scipy import stats  # available in the image; fallback below if not
    m = stats.mode(a, axis=axis, keepdims=keepdim)
    return Tensor(jnp.asarray(m.mode)), Tensor(jnp.asarray(m.count))


def nonzero(x, as_tuple=False):
    a = ensure_tensor(x).numpy()  # dynamic output shape → host sync
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n)) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def masked_select(x, mask, name=None):
    x = ensure_tensor(x)
    m = np.broadcast_to(ensure_tensor(mask).numpy().astype(bool), tuple(x.shape))
    sel = np.nonzero(m.reshape(-1))[0]

    def f(a):
        return jnp.take(a.reshape(-1), jnp.asarray(sel))

    return run_op(f, [x], "masked_select")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    ss, v = ensure_tensor(sorted_sequence), ensure_tensor(values)
    side = "right" if right else "left"

    def f(s):
        return jnp.searchsorted(s, v._value, side=side).astype(
            jnp.int32 if out_int32 else jnp.int32)

    return nondiff_op(f, [ss])


def index_sample(x, index):
    x = ensure_tensor(x)
    ind = ensure_tensor(index)._value.astype(jnp.int32)
    return run_op(lambda a: jnp.take_along_axis(a, ind, axis=1), [x], "index_sample")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
