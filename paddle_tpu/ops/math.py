"""Elementwise & reduction math ops.

Reference parity: `python/paddle/tensor/math.py` + the elementwise/reduce op
corpus (`paddle/fluid/operators/elementwise/`, `operators/reduce_ops/`).
Broadcasting/dtype promotion follow jnp (numpy rules), matching Paddle's.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.flags import flag
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ._dispatch import binary_op, ensure_tensor, inplace_from, run_op, to_arr, unary_op

# ---- binary elementwise ----
add = binary_op(jnp.add, "add")
subtract = binary_op(jnp.subtract, "subtract")
multiply = binary_op(jnp.multiply, "multiply")
divide = binary_op(jnp.divide, "divide")
floor_divide = binary_op(jnp.floor_divide, "floor_divide")
remainder = binary_op(jnp.remainder, "remainder")
mod = remainder
floor_mod = remainder
pow = binary_op(jnp.power, "pow")
maximum = binary_op(jnp.maximum, "maximum")
minimum = binary_op(jnp.minimum, "minimum")
fmax = binary_op(jnp.fmax, "fmax")
fmin = binary_op(jnp.fmin, "fmin")
atan2 = binary_op(jnp.arctan2, "atan2")
heaviside = binary_op(jnp.heaviside, "heaviside")
hypot = binary_op(lambda a, b: jnp.sqrt(a * a + b * b), "hypot")
logaddexp = binary_op(jnp.logaddexp, "logaddexp")
nextafter = binary_op(jnp.nextafter, "nextafter")
copysign = binary_op(jnp.copysign, "copysign")
gcd = binary_op(jnp.gcd, "gcd")
lcm = binary_op(jnp.lcm, "lcm")

elementwise_add = add
elementwise_sub = subtract
elementwise_mul = multiply
elementwise_div = divide

# ---- unary elementwise ----
abs = unary_op(jnp.abs, "abs")
sqrt = unary_op(jnp.sqrt, "sqrt")
rsqrt = unary_op(jax.lax.rsqrt, "rsqrt")
square = unary_op(jnp.square, "square")
exp = unary_op(jnp.exp, "exp")
expm1 = unary_op(jnp.expm1, "expm1")
log = unary_op(jnp.log, "log")
log2 = unary_op(jnp.log2, "log2")
log10 = unary_op(jnp.log10, "log10")
log1p = unary_op(jnp.log1p, "log1p")
sin = unary_op(jnp.sin, "sin")
cos = unary_op(jnp.cos, "cos")
tan = unary_op(jnp.tan, "tan")
asin = unary_op(jnp.arcsin, "asin")
acos = unary_op(jnp.arccos, "acos")
atan = unary_op(jnp.arctan, "atan")
sinh = unary_op(jnp.sinh, "sinh")
cosh = unary_op(jnp.cosh, "cosh")
tanh = unary_op(jnp.tanh, "tanh")
asinh = unary_op(jnp.arcsinh, "asinh")
acosh = unary_op(jnp.arccosh, "acosh")
atanh = unary_op(jnp.arctanh, "atanh")
floor = unary_op(jnp.floor, "floor")
ceil = unary_op(jnp.ceil, "ceil")
round = unary_op(jnp.round, "round")
trunc = unary_op(jnp.trunc, "trunc")
frac = unary_op(lambda a: a - jnp.trunc(a), "frac")
sign = unary_op(jnp.sign, "sign")
reciprocal = unary_op(lambda a: 1.0 / a, "reciprocal")
neg = unary_op(jnp.negative, "neg")
erf = unary_op(jax.lax.erf, "erf")
erfinv = unary_op(jax.lax.erf_inv, "erfinv")
lgamma = unary_op(jax.lax.lgamma, "lgamma")
digamma = unary_op(jax.lax.digamma, "digamma")
angle = unary_op(jnp.angle, "angle")
conj = unary_op(jnp.conj, "conj")
real = unary_op(jnp.real, "real")
imag = unary_op(jnp.imag, "imag")
deg2rad = unary_op(jnp.deg2rad, "deg2rad")
rad2deg = unary_op(jnp.rad2deg, "rad2deg")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = ensure_tensor(x)
    s, b = to_arr(scale), to_arr(bias)
    if bias_after_scale:
        f = lambda a: a * jnp.asarray(s, a.dtype) + jnp.asarray(b, a.dtype)
    else:
        f = lambda a: (a + jnp.asarray(b, a.dtype)) * jnp.asarray(s, a.dtype)
    return run_op(f, [x], "scale")


def clip(x, min=None, max=None, name=None):
    x = ensure_tensor(x)
    lo = to_arr(min) if min is not None else None
    hi = to_arr(max) if max is not None else None
    return run_op(lambda a: jnp.clip(a, lo, hi), [x], "clip")


def lerp(x, y, weight, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return run_op(lambda a, b, w: a + w * (b - a), [x, y, weight], "lerp")
    w = weight
    return run_op(lambda a, b: a + w * (b - a), [x, y], "lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return run_op(lambda a: scale_b * jnp.tanh(scale_a * a), [ensure_tensor(x)], "stanh")


def multiplex(inputs, index, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    idx = ensure_tensor(index)

    def f(*arrs):
        stacked = jnp.stack(arrs, axis=0)
        ind = idx._value.reshape(-1).astype(jnp.int32)
        return stacked[ind, jnp.arange(arrs[0].shape[0])]

    return run_op(f, ts, "multiplex")


# ---- matmul family ----
def _precision():
    p = flag("tpu_matmul_precision")
    return {"default": None, "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}.get(p, None)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        from ..amp.state import maybe_cast
        a, b = maybe_cast(a, b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=_precision())

    return run_op(f, [x, y], "matmul")


mm = matmul


def dot(x, y, name=None):
    return run_op(lambda a, b: jnp.sum(a * b, axis=-1), [ensure_tensor(x), ensure_tensor(y)], "dot")


def outer(x, y, name=None):
    return run_op(lambda a, b: jnp.outer(a, b), [ensure_tensor(x), ensure_tensor(y)], "outer")


def inner(x, y, name=None):
    return run_op(lambda a, b: jnp.inner(a, b), [ensure_tensor(x), ensure_tensor(y)], "inner")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return run_op(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b, precision=_precision()),
        [ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)], "addmm")


def bmm(x, y, name=None):
    return matmul(x, y)


def t(x, name=None):
    x = ensure_tensor(x)
    if x.ndim > 2:
        raise ValueError("t() expects ndim<=2")
    return run_op(lambda a: a.T, [x], "t")


def kron(x, y, name=None):
    return run_op(jnp.kron, [ensure_tensor(x), ensure_tensor(y)], "kron")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return run_op(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                  [ensure_tensor(x)], "trace")


def mv(x, vec, name=None):
    return run_op(lambda a, v: jnp.matmul(a, v, precision=_precision()),
                  [ensure_tensor(x), ensure_tensor(vec)], "mv")


# ---- reductions ----
def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()  # tpu-lint: disable=host-sync (paddle API: Tensor axis -> static ints)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax, dt = _axis_arg(axis), convert_dtype(dtype)
    return run_op(lambda a: jnp.sum(a, axis=ax, dtype=dt, keepdims=keepdim), [x], "sum")


def mean(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), [x], "mean")


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    x = ensure_tensor(x)
    ax, dt = _axis_arg(axis), convert_dtype(dtype)
    return run_op(lambda a: jnp.prod(a, axis=ax, dtype=dt, keepdims=keepdim), [x], "prod")


def max(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.max(a, axis=ax, keepdims=keepdim), [x], "max")


def min(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.min(a, axis=ax, keepdims=keepdim), [x], "min")


amax = max
amin = min


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    dd = 1 if unbiased else 0
    return run_op(lambda a: jnp.std(a, axis=ax, ddof=dd, keepdims=keepdim), [x], "std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    dd = 1 if unbiased else 0
    return run_op(lambda a: jnp.var(a, axis=ax, ddof=dd, keepdims=keepdim), [x], "var")


def median(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.median(a, axis=ax, keepdims=keepdim), [x], "median")


def quantile(x, q, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.quantile(a, jnp.asarray(q), axis=ax, keepdims=keepdim),
                  [x], "quantile")


def nanmean(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), [x], "nanmean")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jnp.nansum(a, axis=ax, dtype=convert_dtype(dtype), keepdims=keepdim),
                  [x], "nansum")


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return run_op(lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim),
                  [x], "logsumexp")


def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = convert_dtype(dtype)
    if axis is None:
        return run_op(lambda a: jnp.cumsum(a.reshape(-1), dtype=dt), [x], "cumsum")
    return run_op(lambda a: jnp.cumsum(a, axis=int(axis), dtype=dt), [x], "cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    dt = convert_dtype(dtype)
    return run_op(lambda a: jnp.cumprod(a, axis=dim, dtype=dt), [x], "cumprod")


def _cum_extreme(x, axis, is_max):
    x = ensure_tensor(x)
    flatten = axis is None
    ax = 0 if flatten else int(axis)

    def f(a):
        if flatten:
            a = a.reshape(-1)
        idx = jnp.broadcast_to(
            jnp.arange(a.shape[ax], dtype=jnp.int32).reshape(
                [-1 if d == (ax % a.ndim) else 1 for d in range(a.ndim)]),
            a.shape)

        def combine(c1, c2):
            v1, i1 = c1
            v2, i2 = c2
            take2 = (v2 >= v1) if is_max else (v2 <= v1)
            return jnp.where(take2, v2, v1), jnp.where(take2, i2, i1)

        return jax.lax.associative_scan(combine, (a, idx), axis=ax)

    vals = run_op(lambda a: f(a)[0], [x], "cummax" if is_max else "cummin")
    from ._dispatch import nondiff_op
    inds = nondiff_op(lambda a: f(a)[1], [x])
    return vals, inds


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, True)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, False)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    pre = to_arr(prepend) if prepend is not None else None
    app = to_arr(append) if append is not None else None
    return run_op(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app), [x], "diff")


# ---- nan/inf checks ----
def isnan(x, name=None):
    from ._dispatch import nondiff_op
    return nondiff_op(jnp.isnan, [ensure_tensor(x)])


def isinf(x, name=None):
    from ._dispatch import nondiff_op
    return nondiff_op(jnp.isinf, [ensure_tensor(x)])


def isfinite(x, name=None):
    from ._dispatch import nondiff_op
    return nondiff_op(jnp.isfinite, [ensure_tensor(x)])


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return run_op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                  [ensure_tensor(x)], "nan_to_num")


# ---- inplace variants (Paddle `op_` spelling) ----
def _make_inplace(op):
    def f(x, *a, **kw):
        return inplace_from(x, op(x, *a, **kw))
    f.__name__ = op.__name__ + "_"
    return f


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
divide_ = _make_inplace(divide)
scale_ = _make_inplace(scale)
clip_ = _make_inplace(clip)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
floor_ = _make_inplace(floor)
ceil_ = _make_inplace(ceil)
round_ = _make_inplace(round)
reciprocal_ = _make_inplace(reciprocal)
tanh_ = _make_inplace(tanh)
abs_ = _make_inplace(abs)


# ---- breadth batch (round 2): reference tensor/math.py stragglers ----
logit = unary_op(jax.scipy.special.logit, "logit")
signbit = unary_op(jnp.signbit, "signbit")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def f(a):
        v = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.cumlogsumexp(v.astype(dtype or v.dtype), axis=ax)

    return run_op(f, [x], "logcumsumexp")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.count_nonzero(a, axis=axis, keepdims=keepdim)
                  .astype(jnp.int32), [x], "count_nonzero")


def nanmedian(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                  [x], "nanmedian")


def cdist(x, y, p=2.0, name=None, **kw):
    """Pairwise p-norm distance between row vectors ([..., M, D] x
    [..., N, D] -> [..., M, N])."""
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            sq = jnp.sum(d * d, -1)
            # exact zero self-distance; sqrt grad guarded off-zero only
            return jnp.where(sq > 0, jnp.sqrt(jnp.maximum(sq, 1e-30)), 0.0)
        if p == float("inf"):
            return jnp.max(jnp.abs(d), -1)  # Chebyshev
        if p == 0.0:
            return jnp.sum((d != 0).astype(a.dtype), -1)  # Hamming
        return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)

    return run_op(f, [x, y], "cdist")


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (`python/paddle/tensor/math.py:971`
    add_n over sum_op)."""
    import functools
    import operator
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    ts = [ensure_tensor(t) for t in inputs]
    return run_op(lambda *arrs: functools.reduce(operator.add, arrs),
                  ts, "add_n")


def increment(x, value=1.0, name=None):
    """x + value, rebinding x's storage (fluid increment op semantics —
    the static-graph loop counter primitive)."""
    x = ensure_tensor(x)
    out = run_op(lambda a: a + jnp.asarray(value, a.dtype), [x], "increment")
    x._value = out._value
    return out


def renorm(x, p, axis, max_norm, name=None):
    """Clamp each sub-tensor along `axis` to p-norm <= max_norm
    (`python/paddle/tensor/math.py` renorm)."""
    x = ensure_tensor(x)

    def f(a):
        axes = tuple(i for i in range(a.ndim) if i != axis)
        norm = jnp.sum(jnp.abs(a.astype(jnp.float32)) ** p, axis=axes,
                       keepdims=True) ** (1.0 / p)
        factor = jnp.where(norm > max_norm, max_norm / (norm + 1e-7), 1.0)
        return (a.astype(jnp.float32) * factor).astype(a.dtype)

    return run_op(f, [x], "renorm")
