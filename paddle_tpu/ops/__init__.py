"""Functional op namespace (the `paddle.tensor` equivalent).

Aggregates all op modules and monkey-patches the method surface onto
`Tensor`, mirroring Paddle's `monkey_patch_varbase`/`monkey_patch_math_varbase`.
"""
from __future__ import annotations

import sys

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from . import random as _random_mod
from .random import (  # noqa: F401
    uniform, uniform_, normal, gaussian, standard_normal, randn, rand, randint,
    randint_like, randperm, bernoulli, multinomial, poisson, exponential_, shuffle,
)

from ..core import tensor as _tensor_mod
from ..core.tensor import Tensor

# late-bind the ops module into Tensor dunders
_tensor_mod._ops = sys.modules[__name__]

_METHODS = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "abs", "sqrt", "rsqrt",
    "square", "exp", "expm1", "log", "log2", "log10", "log1p", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "floor", "ceil", "round", "trunc", "frac", "sign", "reciprocal", "neg", "erf",
    "erfinv", "lgamma", "digamma", "scale", "clip", "lerp", "matmul", "mm", "dot",
    "outer", "inner", "addmm", "bmm", "t", "kron", "trace", "mv",
    "sum", "mean", "prod", "max", "min", "amax", "amin", "std", "var", "median",
    "quantile", "nanmean", "nansum", "logsumexp", "cumsum", "cumprod", "cummax",
    "cummin", "diff", "isnan", "isinf", "isfinite", "nan_to_num",
    "add_", "subtract_", "multiply_", "divide_", "scale_", "clip_", "exp_", "sqrt_",
    "rsqrt_", "floor_", "ceil_", "round_", "reciprocal_", "tanh_", "abs_",
    # manipulation
    "cast", "reshape", "reshape_", "transpose", "swapaxes", "moveaxis", "flatten",
    "squeeze", "unsqueeze", "squeeze_", "unsqueeze_", "split", "chunk", "unbind",
    "gather", "gather_nd", "index_select", "take_along_axis", "put_along_axis",
    "scatter", "scatter_", "scatter_nd_add", "tile", "expand", "expand_as",
    "broadcast_to", "flip", "roll", "rot90", "pad", "repeat_interleave", "unique",
    "masked_fill", "fill_", "fill_diagonal_", "index_put", "as_complex", "as_real",
    # logic
    "equal", "not_equal", "less_than", "less_equal", "greater_than", "greater_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "bitwise_and",
    "bitwise_or", "bitwise_xor", "bitwise_not", "equal_all", "allclose", "isclose",
    "all", "any", "is_empty",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode", "nonzero",
    "masked_select", "index_sample", "bucketize",
    # linalg
    "norm", "dist", "inv", "pinv", "det", "cholesky", "solve", "qr", "svd", "eig",
    "eigh", "matrix_power", "cross", "histogram", "bincount",
    # creation-ish
    "tril", "triu", "diag",
    # random inplace
    "uniform_", "exponential_",
]

_g = globals()
for _name in _METHODS:
    if _name in _g and not hasattr(Tensor, _name):
        setattr(Tensor, _name, _g[_name])

# a few method-only aliases
Tensor.rsub = lambda self, y: subtract(y, self)  # noqa: E731
Tensor.item_ = Tensor.item
