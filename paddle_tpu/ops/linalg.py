"""Linear algebra + einsum.

Reference parity: `python/paddle/tensor/linalg.py` and `paddle.linalg.*`.
Heavy decompositions (svd/qr/eigh/...) lower to XLA's native decomposition
custom-calls; on TPU some run via CPU callback inside XLA — same trade-off
the reference makes by calling cuSOLVER.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._dispatch import ensure_tensor, nondiff_op, run_op

from .math import matmul, dot, t, bmm, mv  # re-export for paddle.linalg namespace


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2

    def f(a):
        if axis is None:
            flat = a.reshape(-1)
            if p == "fro" or p == 2:
                return jnp.sqrt(jnp.sum(flat * flat)) if not keepdim else \
                    jnp.sqrt(jnp.sum(flat * flat)).reshape([1] * a.ndim)
            if p == float("inf"):
                return jnp.max(jnp.abs(flat))
            if p == float("-inf"):
                return jnp.min(jnp.abs(flat))
            if p == 1:
                return jnp.sum(jnp.abs(flat))
            if p == 0:
                return jnp.sum((flat != 0).astype(a.dtype))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), p)), 1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        return jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim)

    return run_op(f, [x], "norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


def dist(x, y, p=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return run_op(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), [x, y], "dist")


def cond(x, p=None, name=None):
    return nondiff_op(lambda a: jnp.linalg.cond(a, p=p), [ensure_tensor(x)])


def inv(x, name=None):
    return run_op(jnp.linalg.inv, [ensure_tensor(x)], "inv")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return run_op(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                  [ensure_tensor(x)], "pinv")


def det(x, name=None):
    return run_op(jnp.linalg.det, [ensure_tensor(x)], "det")


def slogdet(x, name=None):
    x = ensure_tensor(x)
    outs = run_op(lambda a: tuple(jnp.linalg.slogdet(a)), [x], "slogdet")
    return outs


def cholesky(x, upper=False, name=None):
    x = ensure_tensor(x)

    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l

    return run_op(f, [x], "cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def f(b, l):
        lo = jnp.swapaxes(l, -1, -2) if upper else l
        z = jax.scipy.linalg.solve_triangular(lo, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(lo, -1, -2), z, lower=False)

    return run_op(f, [x, y], "cholesky_solve")


def solve(x, y, name=None):
    return run_op(jnp.linalg.solve, [ensure_tensor(x), ensure_tensor(y)], "solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return run_op(
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular),
        [x, y], "triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    sol, res, rank, sv = jnp.linalg.lstsq(x._value, y._value, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def qr(x, mode="reduced", name=None):
    x = ensure_tensor(x)
    q, r = jnp.linalg.qr(x._value, mode=mode)
    return Tensor(q), Tensor(r)


def svd(x, full_matrices=False, name=None):
    x = ensure_tensor(x)
    u, s, vh = jnp.linalg.svd(x._value, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2))


def eig(x, name=None):
    x = ensure_tensor(x)
    w, v = jnp.linalg.eig(x._value)
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    w, v = jnp.linalg.eigh(x._value, UPLO=UPLO)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    return Tensor(jnp.linalg.eigvals(ensure_tensor(x)._value))


def eigvalsh(x, UPLO="L", name=None):
    return Tensor(jnp.linalg.eigvalsh(ensure_tensor(x)._value, UPLO=UPLO))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return nondiff_op(lambda a: jnp.linalg.matrix_rank(a, rtol=tol), [ensure_tensor(x)])


def matrix_power(x, n, name=None):
    return run_op(lambda a: jnp.linalg.matrix_power(a, n), [ensure_tensor(x)], "matrix_power")


def multi_dot(x, name=None):
    ts = [ensure_tensor(a) for a in x]
    return run_op(lambda *arrs: jnp.linalg.multi_dot(arrs), ts, "multi_dot")


def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis if axis != 9 else (next((i for i, s in enumerate(x.shape) if s == 3), -1))
    return run_op(lambda a, b: jnp.cross(a, b, axis=ax), [x, y], "cross")


def householder_product(x, tau, name=None):
    """Q = H_0 H_1 ... H_{n-1} from compact Householder reflectors
    (`python/paddle/tensor/linalg.py` householder_product over the orgqr
    LAPACK contract): x [*, m, n] holds v_i below the diagonal of column
    i (implicit unit diagonal), tau [*, n] the scalar factors; returns the
    first n columns of Q [*, m, n]. Static python loop over the n
    reflectors — each step is one rank-1 update (matmul-shaped, MXU-
    friendly); batches broadcast through."""
    x, tau = ensure_tensor(x), ensure_tensor(tau)

    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        batch = a.shape[:-2]
        eye = jnp.broadcast_to(jnp.eye(m, n, dtype=a.dtype),
                               batch + (m, n))
        rows = jnp.arange(m)
        q = eye
        for i in reversed(range(n)):
            v = jnp.where((rows > i)[..., None],
                          a[..., :, i:i + 1], 0.0)
            v = v.at[..., i, 0].set(1.0) if not batch else \
                v.at[..., i, :].set(1.0)
            ti = t[..., i:i + 1, None] if t.ndim > 1 else t[i]
            # H_i @ q = q - tau_i * v (v^T q)
            q = q - ti * v @ (jnp.swapaxes(v, -1, -2) @ q)
        return q

    return run_op(f, [x, tau], "householder_product")


def corrcoef(x, rowvar=True, name=None):
    return run_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), [ensure_tensor(x)], "corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return run_op(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
                  [ensure_tensor(x)], "cov")


def einsum(equation, *operands):
    ts = [ensure_tensor(o) for o in operands]
    return run_op(lambda *arrs: jnp.einsum(equation, *arrs), ts, "einsum")


def histogram(input, bins=100, min=0, max=0, name=None):
    a = ensure_tensor(input)
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = jnp.histogram(a._value, bins=bins, range=rng)
    return Tensor(h)


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    w = ensure_tensor(weights)._value if weights is not None else None
    return Tensor(jnp.bincount(x._value.astype(jnp.int32), weights=w, minlength=minlength))


def inverse(x, name=None):
    x = ensure_tensor(x)
    return run_op(jnp.linalg.inv, [x], "inverse")


def tensordot(x, y, axes=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(axes, (list, tuple)):
        if all(isinstance(a, int) for a in axes):
            # flat int list: contract these axes of BOTH tensors
            ax = (tuple(axes), tuple(axes))
        else:
            ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else (a,)
                       for a in axes)
    else:
        ax = axes
    return run_op(lambda a, b: jnp.tensordot(a, b, axes=ax), [x, y],
                  "tensordot")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.linalg.matrix_rank(a, rtol=tol),
                  [x], "matrix_rank")


def rank(x, name=None):
    """Number of dimensions (fluid layers.rank parity)."""
    x = ensure_tensor(x)
    return run_op(lambda a: jnp.asarray(a.ndim, jnp.int32), [x], "rank")
