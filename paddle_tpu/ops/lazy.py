"""Lazy batching eager executor — kill the per-op dispatch tax.

Reference parity: the final-state eager dygraph (`paddle/fluid/eager/`)
retired fluid's per-op Tracer round trip; on a tunneled TPU the analogous
tax is one cached-XLA-executable dispatch per primitive chain
(`ops/_dispatch.run_op`), ~one RTT per op. This module retires it the
TPU-native way: under ``FLAGS_lazy_eager``, ``run_op``/``nondiff_op`` stop
executing and instead append ``(fn, inputs, name)`` records to a per-thread
:class:`LazySegment`; output Tensors carry a :class:`_LazyValue` pending
payload. At a *sync point* — exactly the sites tpu-lint's host-sync /
tensor-branch rules enumerate (``.numpy()``/``.item()``/``float()``/
``bool()``/print, control flow on tensor values, ``backward()``,
``paddle.sync()``) — the segment is topologically closed, keyed by its
op-sequence + leaf shape/dtype signature, compiled once into a single
jitted replay, and dispatched as ONE executable. Steady-state eager steps
therefore dispatch O(1) executables instead of O(ops).

The tape keeps working: a deferred diff op records its node immediately
(against the lazy outputs) with a :class:`_PendingVJP` placeholder; the
flush patches every placeholder to a real :class:`autograd._JitVJP` whose
residuals came out of the same jitted replay, so ``backward()`` (which
flushes first) runs the normal — and, for repeating tapes, fused — walk.

Fallbacks (each op, decided at defer time; counted as
``lazy.fallback_ops``): inputs already tracers (inside a jax trace), an
op closure that cannot be value-keyed (`autograd._fn_key` raises), an op
whose shapes cannot be abstractly evaluated, or a diff op mixing a
non-stop-gradient integer input. Fallback materializes pending inputs and
lets the immediate path run the op, preserving eager semantics bit-for-bit.

Accounting (FLAGS_monitor): ``lazy.ops_deferred``, ``lazy.flushes``,
``lazy.dispatches``, ``lazy.ops_flushed``, ``lazy.cache_hits``,
``lazy.fallback_ops``, plus ``jit.lazy_segment.traces``/``.retraces``
via ``monitor.record_retrace`` (the shared ``core/executable.py``
ledger regime). Observability: each flush is booked on
the step timeline as one ``trace_compile`` (novel signature) or
``device_compute`` (cache hit) phase — not smeared per-op.
"""
from __future__ import annotations

import sys
import threading
from collections import OrderedDict
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from ..core import autograd
from ..core import compile_cache as _cc
from ..core import executable as _exe
from ..core import flags as _flags
from ..core import tensor as _tensor_mod
from ..core.tensor import Tensor

__all__ = ["LazySegment", "flush_pending", "pending_ops", "sync"]


class _LazyValue:
    """Pending payload of a deferred op's output Tensor.

    Carries the abstract value (shape/dtype) so metadata reads stay free;
    any *data* read (`__array__`/`__jax_array__`/`block_until_ready`)
    flushes the owning segment and resolves to the concrete array. After
    the flush, `_arr` is set so stale aliases (detach/clone sharing the
    payload) keep resolving without touching the dead segment.
    """

    __slots__ = ("_arr", "_seg", "_ridx", "_oidx", "shape", "dtype",
                 "weak_type", "_ts")

    def __init__(self, seg: "LazySegment", ridx: int, oidx: int, aval):
        self._arr = None
        self._seg = seg
        self._ridx = ridx
        self._oidx = oidx
        self.shape = tuple(aval.shape)
        self.dtype = np.dtype(aval.dtype)
        self.weak_type = bool(getattr(aval, "weak_type", False))
        self._ts: List[Tensor] = []   # tensors to patch concrete at flush

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def _resolve(self):
        if self._arr is None:
            self._seg.flush()
        return self._arr

    # ---- sync points: any data access materializes the segment ----
    def __array__(self, dtype=None):
        a = np.asarray(self._resolve())
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._resolve()

    def block_until_ready(self):
        a = self._resolve()
        if hasattr(a, "block_until_ready"):
            a.block_until_ready()
        return a

    def __repr__(self):
        state = "materialized" if self._arr is not None else "pending"
        return f"<lazy {state} {self.dtype.name}{list(self.shape)}>"


class _PendingVJP:
    """Tape placeholder for a deferred diff op's VJP: invoking it (an eager
    backward reaching an unflushed node) flushes the segment, which patches
    in the real `_JitVJP`; delegate to it."""

    __slots__ = ("seg", "resolved")

    def __init__(self, seg: "LazySegment"):
        self.seg = seg
        self.resolved = None

    def __call__(self, cts):
        if self.resolved is None:
            self.seg.flush()
        if self.resolved is None:      # flush died before reaching this op
            raise RuntimeError("lazy VJP unresolved after segment flush")
        return self.resolved(cts)


class _Record:
    """One deferred op: how to re-derive its inputs inside the replay and
    where to deliver its outputs/VJP afterwards."""

    __slots__ = ("fn", "name", "kind", "bindings", "inexact", "multi",
                 "lvs", "node", "pending", "key", "nan_check")

    def __init__(self, fn, name, kind, bindings, inexact, multi, lvs,
                 node, pending, key, nan_check):
        self.fn = fn
        self.name = name
        self.kind = kind            # "vjp" | "vjp_split" | "primal" | "nondiff"
        self.bindings = bindings    # tuple of ("l", leaf_idx) | ("r", rec, out)
        self.inexact = inexact      # tuple[bool] for vjp_split, else None
        self.multi = multi          # fn returns a tuple
        self.lvs = lvs              # output _LazyValues, positional
        self.node = node            # tape Node (diff records) or None
        self.pending = pending      # _PendingVJP installed on the node
        self.key = key              # hashable replay-cache component
        self.nan_check = nan_check  # FLAGS_check_nan_inf was on at defer


# ---- segment signature cache (executable-substrate ledger) ----------------
# LRU-ordered: a flush hit moves the signature to the MRU end, overflow
# evicts from the LRU end one entry at a time (the old wholesale .clear()
# threw away every hot replay whenever one workload overflowed the cap).
# Replaces the private _SEG_CACHE/_SEG_SEEN pair with the shared
# core/executable.py ledger; the monitor eviction counter keeps its name.


def _count_eviction(_sig, _replay) -> None:
    if _monitor._ENABLED:
        _monitor.count("lazy.cache_evictions")


_LEDGER = _exe.ExecutableLedger(
    "lazy_segment",
    cap=max(1, int(_flags.flag("lazy_cache_entries"))),
    on_evict=_count_eviction)


def _on_cache_entries(value) -> None:
    _LEDGER.set_cap(max(1, int(value)))


_flags.watch_flag("lazy_cache_entries", _on_cache_entries)
# (fn-id component, input aval sig) -> output ShapeDtypeStructs
_SHAPE_CACHE: dict = {}
_SHAPE_CACHE_CAP = 8192

_FALLBACK = object()   # sentinel: dispatch must run the op immediately

# Hot-path gate: ops/_dispatch reads this module attribute; one attribute
# load is the entire disabled-path cost (PR 1 monitor._ENABLED regime).
_ACTIVE: bool = bool(_flags.flag("lazy_eager"))
_MAX_OPS: int = int(_flags.flag("lazy_max_segment_ops"))


def _on_max_ops(value) -> None:
    global _MAX_OPS
    _MAX_OPS = int(value)


_flags.watch_flag("lazy_max_segment_ops", _on_max_ops)


class _TLS(threading.local):
    def __init__(self):
        self.seg: Optional[LazySegment] = None


_STATE = _TLS()


def _segment() -> "LazySegment":
    seg = _STATE.seg
    if seg is None:
        seg = _STATE.seg = LazySegment()
    return seg


def _on_flag(value) -> None:
    global _ACTIVE
    on = bool(value)
    if _ACTIVE and not on:
        flush_pending()            # turning lazy off is itself a sync point
    _ACTIVE = on


_flags.watch_flag("lazy_eager", _on_flag)


def pending_ops() -> int:
    """Deferred-op count in the calling thread's segment (0 = drained)."""
    seg = _STATE.seg
    return 0 if seg is None else len(seg.records)


def flush_pending() -> None:
    """Flush the calling thread's pending segment (no-op when drained)."""
    seg = _STATE.seg
    if seg is not None and seg.records:
        seg.flush()


def sync() -> None:
    """Explicit sync point (`paddle.sync()`): flush the pending lazy
    segment so every deferred op is executed and materialized."""
    flush_pending()


def segment_memory() -> List[dict]:
    """Compiler-reported memory breakdown for every cached segment replay
    executable (obs.executable_memory), MRU last. Each signature carries
    its leaf avals, so the replays AOT-lower without live inputs."""
    from .. import obs as _obs_pkg
    out = []
    for sig, replay in _LEDGER.items():
        structs = [jax.ShapeDtypeStruct(tuple(shape), np.dtype(dt),
                                        weak_type=wt)
                   for shape, dt, wt in sig[1]]
        try:
            rep = _obs_pkg.executable_memory(replay.lower(structs).compile())
        except Exception:
            continue
        out.append({"ops": len(sig[0]), "leaves": len(structs), **rep})
    return out


def _aval_of(v):
    return jax.ShapeDtypeStruct(
        v.shape, v.dtype, weak_type=bool(getattr(v, "weak_type", False)))


def _out_shapes(fn, fkey, in_avals):
    """eval_shape with a value-keyed cache; None when fn is untraceable."""
    sig = (fkey, tuple((a.shape, str(a.dtype)) for a in in_avals))
    try:
        hit = sig in _SHAPE_CACHE
    except TypeError:
        hit = False
        sig = None
    if hit:
        return _SHAPE_CACHE[sig]
    try:
        out = jax.eval_shape(fn, *in_avals)
    except Exception:
        return None
    if sig is not None:
        if len(_SHAPE_CACHE) >= _SHAPE_CACHE_CAP:
            _SHAPE_CACHE.clear()
        _SHAPE_CACHE[sig] = out
    return out


def _materialize_inputs(tensors) -> None:
    """Resolve any pending payloads so the immediate path sees arrays."""
    for t in tensors:
        v = t._value
        if type(v) is _LazyValue:
            t._value = v._resolve()


def _scan_nan_inf(name: str, arrs) -> None:
    # FLAGS_check_nan_inf parity for deferred ops: the per-op scan is
    # re-run over the flushed outputs (attribution by op name survives;
    # only the *timing* of the abort moves to the sync point).
    for i, o in enumerate(arrs):
        if jnp.issubdtype(o.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(o))):  # tpu-lint: disable=host-sync (debug-only deferred NaN scan)
                raise FloatingPointError(
                    f"Operator {name} output {i} contains NaN/Inf "
                    "(FLAGS_check_nan_inf=True, detected at lazy flush)")


class LazySegment:
    """Per-thread accumulator of deferred ops and their dataflow.

    `leaves` are the concrete arrays entering the segment (deduped by
    identity); each record's inputs are bindings into the leaf list or
    into an earlier record's outputs, so the whole segment replays as a
    pure function of the leaves — compiled once per (op-sequence, leaf
    signature) and re-dispatched from the module segment ledger thereafter.
    """

    __slots__ = ("records", "leaves", "leaf_ids", "_flushing")

    def __init__(self):
        self.records: List[_Record] = []
        self.leaves: List[Any] = []
        self.leaf_ids: dict = {}
        self._flushing = False

    # ---- record side -----------------------------------------------------
    def _bind(self, v):
        """Binding for one input payload (concrete array or _LazyValue)."""
        if type(v) is _LazyValue:
            if v._arr is not None:
                v = v._arr                       # already materialized
            elif v._seg is not self:
                v = v._resolve()                 # cross-thread tensor: sync
            else:
                return ("r", v._ridx, v._oidx)
        i = self.leaf_ids.get(id(v))
        if i is None:
            i = self.leaf_ids[id(v)] = len(self.leaves)
            self.leaves.append(v)
        return ("l", i)

    def defer(self, fn, tensors, name, kind, inexact, record):
        """Append one op; returns wrapped output Tensor(s) or _FALLBACK."""
        try:
            fkey = autograd._fn_key(fn)
        except autograd._Uncacheable:
            _materialize_inputs(tensors)
            if _monitor._ENABLED:
                _monitor.count("lazy.fallback_ops")
            return _FALLBACK
        in_avals = [_aval_of(t._value) for t in tensors]
        out = _out_shapes(fn, fkey, in_avals)
        if out is None:
            _materialize_inputs(tensors)
            if _monitor._ENABLED:
                _monitor.count("lazy.fallback_ops")
            return _FALLBACK
        if len(self.records) >= _MAX_OPS:
            self.flush()
        multi = isinstance(out, tuple)
        out_avals = out if multi else (out,)
        bindings = tuple(self._bind(t._value) for t in tensors)
        ridx = len(self.records)
        lvs = [_LazyValue(self, ridx, i, a) for i, a in enumerate(out_avals)]
        out_tensors = [Tensor(lv) for lv in lvs]
        for lv, t in zip(lvs, out_tensors):
            lv._ts.append(t)
        node = pending = None
        if record:
            pending = _PendingVJP(self)
            node = autograd.record_node(pending, tensors, out_tensors,
                                        name, fn=fn)
        key = (kind, fkey, bindings, inexact, multi)
        self.records.append(_Record(
            fn, name, kind, bindings, inexact, multi, lvs, node, pending,
            key, _flags.flag("check_nan_inf")))
        if _monitor._ENABLED:
            _monitor.count("lazy.ops_deferred")
        if multi:
            return tuple(out_tensors)
        return out_tensors[0]

    # ---- flush side ------------------------------------------------------
    def flush(self) -> None:
        """Sync point: close the segment, dispatch it as one executable,
        and deliver outputs/VJPs back onto the recorded tensors/tape."""
        if self._flushing or not self.records:
            return
        self._flushing = True
        records, leaves = self.records, self.leaves
        self.records, self.leaves, self.leaf_ids = [], [], {}
        try:
            sig = (tuple(r.key for r in records),
                   tuple((tuple(a.shape), str(a.dtype),
                          bool(getattr(a, "weak_type", False)))
                         for a in leaves))
            replay = _LEDGER.get(sig)
            novel = not _LEDGER.seen(sig)
            if _monitor._ENABLED:
                _monitor.count("lazy.flushes")
                _monitor.count("lazy.dispatches")
                _monitor.count("lazy.ops_flushed", len(records))
                if not novel:
                    _monitor.count("lazy.cache_hits")
            if novel:
                _LEDGER.note(sig, detail=(
                    (f"ops={len(records)}",)
                    + _monitor.arg_signature(leaves))
                    if _monitor._ENABLED else None)
            with _exe.booking("lazy_segment") as bk:
                if replay is None:
                    replay = _build_replay(records)
                    source = "fresh"
                    if _cc.enabled() and all(
                            r.kind in ("primal", "nondiff")
                            for r in records):
                        # only sync-free segments persist: a diff segment's
                        # replay returns jax.vjp closures, which the export
                        # path cannot serialize (they'd count export_skips
                        # for every flush — skip upfront instead)
                        replay, source = _exe.acquire(
                            "lazy_segment", replay, (leaves,),
                            label=f"ops={len(records)}")
                    _LEDGER.put(sig, replay)
                    if novel and source == "fresh":
                        bk.compiled()
                elif novel:
                    bk.compiled()
                out_groups, vjp_raws = replay(leaves)
            if _mem._ENABLED:
                _mem.tag("lazy_segment",
                         [arr for outs in out_groups for arr in outs],
                         origin=f"LazySegment.flush ops={len(records)}")
            # deliver: materialize payloads, rebind tensors, patch VJPs
            for rec, outs, raw in zip(records, out_groups, vjp_raws):
                for lv, arr in zip(rec.lvs, outs):
                    lv._arr = arr
                    for t in lv._ts:
                        if type(t._value) is _LazyValue:
                            t._value = arr
                if rec.node is not None:
                    jv = autograd._JitVJP(raw, rec.inexact)
                    rec.pending.resolved = jv
                    if rec.node.vjp_fn is rec.pending:
                        rec.node.vjp_fn = jv
            for rec, outs in zip(records, out_groups):
                if rec.nan_check:
                    _scan_nan_inf(rec.name, outs)
        finally:
            self._flushing = False


def _build_replay(records):
    """Jit the whole segment as one pure function of its leaves, returning
    every record's outputs plus the VJP residuals of the diff records
    (jax.vjp's closure is a pytree over a static treedef, so it rides out
    of the jit — the `autograd._cached_jit(kind='vjp')` precedent)."""
    specs = tuple((r.kind, r.fn, r.inexact, r.bindings) for r in records)

    def replay(leaves):
        vals: List[tuple] = []
        vjps: List[Any] = []
        for kind, fn, inexact, bindings in specs:
            ins = [leaves[b[1]] if b[0] == "l" else vals[b[1]][b[2]]
                   for b in bindings]
            if kind == "vjp":
                outs, raw = jax.vjp(fn, *ins)
            elif kind == "vjp_split":
                outs, raw = autograd._split_vjp_builder(fn, inexact)(*ins)
            else:
                outs, raw = fn(*ins), None
            vals.append(outs if isinstance(outs, tuple) else (outs,))
            vjps.append(raw)
        return vals, vjps

    return jax.jit(replay)


def defer_op(fn, tensors, name):
    """run_op front half under FLAGS_lazy_eager. Returns Tensor(s) or
    _FALLBACK (after materializing pending inputs) when the op must run
    immediately."""
    seg = _segment()
    arrays = [t._value for t in tensors]
    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        _materialize_inputs(tensors)   # inside a jax trace: let JAX see it
        return _FALLBACK
    record = autograd._STATE.enabled and any(
        not t.stop_gradient for t in tensors)
    if not record:
        return seg.defer(fn, tensors, name, "primal", None, False)
    inexact = tuple(
        bool(jnp.issubdtype(a.dtype, jnp.inexact)) for a in arrays)
    if all(inexact):
        return seg.defer(fn, tensors, name, "vjp", None, True)
    if all(t.stop_gradient or f for t, f in zip(tensors, inexact)):
        return seg.defer(fn, tensors, name, "vjp_split", inexact, True)
    # differentiating through an integer input (float0 cotangents): rare —
    # keep exact immediate-mode semantics rather than teach the replay
    _materialize_inputs(tensors)
    if _monitor._ENABLED:
        _monitor.count("lazy.fallback_ops")
    return _FALLBACK


def defer_nondiff(fn, tensors):
    """nondiff_op front half under FLAGS_lazy_eager."""
    seg = _segment()
    if any(isinstance(t._value, jax.core.Tracer) for t in tensors):
        _materialize_inputs(tensors)
        return _FALLBACK
    return seg.defer(fn, tensors, "nondiff", "nondiff", None, False)


# Wire the pending-payload type into Tensor construction (no isinstance
# cost added to the non-lazy path: it extends the existing accepted-types
# tuple) and give autograd its flush-at-backward hook.
_tensor_mod._VALUE_TYPES = _tensor_mod._VALUE_TYPES + (_LazyValue,)
autograd._LAZY = sys.modules[__name__]
