"""Typed failure taxonomy of the training guard plane.

Reference parity: the enforce layer (`paddle/fluid/platform/enforce.h`)
turns raw crashes into typed, catchable exceptions; the guard does the
same for the three failure modes a clean exception never covers on a pod
slice — preemption, a wedged step/collective, and silent numeric or
cross-rank divergence. Every error carries enough context (phase, step,
offending ranks, checkpoint path) for the relauncher to decide between
resume, rollback, and abort without parsing log text.
"""
from __future__ import annotations

from typing import List, Optional, Tuple


class GuardError(RuntimeError):
    """Base of every guard-plane failure."""


class PreemptedError(GuardError):
    """A preemption signal (SIGTERM/SIGINT) arrived; the in-flight step was
    finished and the full loop state was committed to `ckpt_dir`. Re-running
    with `TrainGuard.resume()` continues bit-identically from `cursor`."""

    def __init__(self, signum: int, ckpt_dir: Optional[str],
                 cursor: Tuple[int, int]):
        self.signum = signum
        self.ckpt_dir = ckpt_dir
        self.cursor = cursor
        where = f"epoch {cursor[0]}, batch {cursor[1]}"
        saved = f"; loop state checkpointed to {ckpt_dir}" if ckpt_dir \
            else " (no ckpt_dir configured — state NOT saved)"
        super().__init__(
            f"training preempted by signal {signum} at {where}{saved}; "
            f"call TrainGuard.resume() after relaunch")


class StepStalledError(GuardError):
    """The step watchdog deadline expired with the step still running —
    a hung collective / wedged device surfaced as a typed error instead of
    an infinite hang. `phase` is the last phase the step reported."""

    def __init__(self, phase: str, deadline_s: float, step: int):
        self.phase = phase
        self.deadline_s = deadline_s
        self.step = step
        super().__init__(
            f"train step {step} exceeded its {deadline_s:.3f}s watchdog "
            f"deadline (last-known phase: {phase!r}); the step thread is "
            f"likely wedged in a hung collective or device transfer")


class DivergedError(GuardError):
    """`max_bad_steps` consecutive steps produced a non-finite or spiking
    loss even after rollback to the last-good snapshot — the run has
    genuinely diverged and skipping batches no longer helps."""

    def __init__(self, bad_steps: int, last_loss, step: int):
        self.bad_steps = bad_steps
        self.last_loss = last_loss
        self.step = step
        super().__init__(
            f"training diverged: {bad_steps} consecutive bad steps up to "
            f"step {step} (last loss {last_loss}); params were rolled back "
            f"to the last-good snapshot each time")


class RankDesyncError(GuardError):
    """Parameter fingerprints disagree across the data-parallel group —
    some rank silently diverged (bit flip, lost collective, nondeterministic
    kernel). Names the offending rank(s): the minority side of the
    fingerprint vote (ties broken toward the lowest rank's value)."""

    def __init__(self, step: int, offenders: List[int], fingerprints):
        self.step = step
        self.offenders = list(offenders)
        self.fingerprints = dict(fingerprints)
        super().__init__(
            f"cross-rank parameter desync at step {step}: rank(s) "
            f"{self.offenders} disagree with the group "
            f"(fingerprints: {self.fingerprints})")


# ---- flight-recorder dump triggers (paddle_tpu.obs) -------------------------
# Every guard failure must leave a black box behind: each error type
# registers its dump reason here, and the raise sites call
# `obs.dump_on_error(exc)` — which (when FLAGS_obs_flight_recorder is on)
# writes the artifact and appends its path to the error message. A tier-1
# test walks GuardError.__subclasses__ and fails on any class without a
# trigger (directly or inherited), so a future guard error without
# forensics cannot ship.
from .. import obs as _obs  # noqa: E402

_obs.register_dump_trigger(PreemptedError, "preempted")
_obs.register_dump_trigger(StepStalledError, "step_stalled")
_obs.register_dump_trigger(DivergedError, "diverged")
_obs.register_dump_trigger(RankDesyncError, "rank_desync")
