"""Step watchdog — converts a hung step into a typed StepStalledError.

A hung XLA dispatch (wedged collective, dead tunnel, stuck host callback)
blocks the calling thread in C and cannot be interrupted in place, so the
watchdog runs each step on a dedicated runner thread and bounds the wait on
the caller side: when the deadline expires the caller gets a
`StepStalledError` carrying the last-known phase while the wedged runner is
abandoned (a fresh runner serves subsequent steps; a late result from the
abandoned one is discarded by sequence number).

The deadline is `FLAGS_guard_step_timeout_s` when set, otherwise
auto-calibrated as `max(FLAGS_guard_min_timeout_s, FLAGS_guard_timeout_factor
x trailing-median step duration)` once `FLAGS_guard_warmup_steps` steps have
completed — compile-heavy first steps inflate the median far less than the
max, and the factor absorbs retraces. With no deadline yet (warmup, auto
mode) steps run inline on the caller thread: zero overhead, no thread.
"""
from __future__ import annotations

import queue
import statistics
import threading
import time
from typing import List, Optional

from .. import monitor as _monitor
from .. import obs as _obs
from .errors import StepStalledError
from ..utils import syncwatch as _syncwatch


class StepWatchdog:
    """Deadline supervisor for one training loop. Not thread-safe: one
    loop, one watchdog. `run(fn, *args)` executes fn under the current
    deadline; `phase(name)` tags progress so a stall names where it hung;
    `close()` joins the runner (and any wedged stragglers) within a grace
    period so tests never leak `guard-*` threads."""

    def __init__(self, timeout_s: float = 0.0, warmup_steps: int = 5,
                 factor: float = 10.0, min_timeout_s: float = 30.0,
                 history: int = 64):
        self._timeout = float(timeout_s)
        self._warmup = int(warmup_steps)
        self._factor = float(factor)
        self._min_timeout = float(min_timeout_s)
        self._durations: List[float] = []
        self._history = int(history)
        self._phase = "idle"
        self._step = 0
        self._seq = 0
        self._jobs: Optional[queue.Queue] = None
        self._results: Optional[queue.Queue] = None
        self._runner: Optional[threading.Thread] = None
        self._wedged: List[threading.Thread] = []
        self._closed = False

    # ---- phase + deadline ----
    def phase(self, name: str) -> None:
        self._phase = name
        if _obs._ENABLED:
            # timeline marker: a wedge between phase spans still gets its
            # last-known position into the flight-recorder dump
            _obs.mark(name)

    def record(self, duration_s: float) -> None:
        self._durations.append(float(duration_s))
        if len(self._durations) > self._history:
            del self._durations[:-self._history]

    def deadline(self) -> Optional[float]:
        """Current per-step deadline in seconds, or None (not armed yet)."""
        if self._timeout > 0:
            return self._timeout
        if len(self._durations) >= max(1, self._warmup):
            med = statistics.median(self._durations)
            return max(self._min_timeout, self._factor * med)
        return None

    # ---- runner thread ----
    def _ensure_runner(self) -> None:
        if self._runner is not None and self._runner.is_alive():
            return
        self._jobs = queue.Queue()
        self._results = queue.Queue()
        jobs, results = self._jobs, self._results

        def loop():
            while True:
                job = jobs.get()
                if job is None:
                    return
                seq, fn, args, kwargs = job
                try:
                    results.put((seq, True, fn(*args, **kwargs)))
                except BaseException as e:  # noqa: BLE001 — marshalled to caller
                    results.put((seq, False, e))

        self._runner = _syncwatch.Thread(target=loop, daemon=True,
                                        name="guard-watchdog-runner")
        self._runner.start()

    def run(self, fn, *args, **kwargs):
        """Execute fn under the current deadline; raises StepStalledError
        on expiry, re-raises fn's own exception otherwise."""
        if self._closed:
            raise RuntimeError("StepWatchdog is closed")
        self._step += 1
        dl = self.deadline()
        t0 = time.monotonic()
        if dl is None:  # warmup / auto not armed: inline, no thread
            out = fn(*args, **kwargs)
            self.record(time.monotonic() - t0)
            return out
        self._ensure_runner()
        self._seq += 1
        seq = self._seq
        self._jobs.put((seq, fn, args, kwargs))
        while True:
            remaining = dl - (time.monotonic() - t0)
            if remaining <= 0:
                break
            try:
                rseq, ok, val = self._results.get(timeout=remaining)
            except queue.Empty:
                break
            if rseq != seq:
                continue  # stale result from a previously-wedged step
            self.record(time.monotonic() - t0)
            if ok:
                return val
            raise val
        # deadline expired: abandon the runner (it is blocked inside fn).
        # The sentinel makes it exit its loop if/when fn ever returns —
        # without it the straggler would block forever on the dead queue.
        self._jobs.put(None)
        self._wedged.append(self._runner)
        self._runner = None
        if _monitor._ENABLED:
            _monitor.count("guard.stalls")
        err = StepStalledError(phase=self._phase, deadline_s=dl,
                               step=self._step)
        if _obs._FR_ENABLED:
            # black box FIRST, while the wedged step is still in flight —
            # the dump's inflight_phase/open_step name where it hung
            _obs.record_event("guard.stall", phase=self._phase,
                              step=self._step, deadline_s=dl)
            _obs.dump_on_error(err)
        raise err

    # ---- lifecycle ----
    def alive_threads(self) -> List[threading.Thread]:
        out = [t for t in self._wedged if t.is_alive()]
        if self._runner is not None and self._runner.is_alive():
            out.append(self._runner)
        return out

    def close(self, grace_s: float = 5.0) -> None:
        """Stop the runner and join stragglers. A still-wedged thread after
        the grace period is left daemonized (it cannot be killed) but is
        reported via the return-less assert in tests' leak guard."""
        self._closed = True
        if self._runner is not None and self._jobs is not None:
            self._jobs.put(None)
        deadline = time.monotonic() + grace_s
        for t in ([self._runner] if self._runner else []) + self._wedged:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._wedged = [t for t in self._wedged if t.is_alive()]
        if self._runner is not None and not self._runner.is_alive():
            self._runner = None
