"""TrainGuard — the training-loop supervisor.

Wraps a `jit.TrainStep` / `parallel.SPMDTrainStep` (and, through
`hapi.Model.fit(guard=...)`, the whole fit loop) with the four guards the
paper's long-running pod-slice runs need:

  1. preemption-safe auto-resume — SIGTERM/SIGINT set a flag; the in-flight
     step FINISHES, then the full loop state (params, optimizer slots,
     GradScaler streaks, LR-scheduler step, both rng streams, epoch+batch
     cursor) is committed crash-atomically (`guard/checkpoint.py`) and
     `PreemptedError` is raised. `resume()` restores every piece, so an
     interrupted run produces bit-identical params to an uninterrupted one.
  2. step watchdog — each step runs under `StepWatchdog`'s deadline
     (explicit flag or trailing-median auto-calibration); a wedged step
     surfaces as `StepStalledError` with the last-known phase.
  3. divergence guard — a non-finite loss (including the traced
     FLAGS_check_nan_inf raise) or a spike beyond
     `FLAGS_guard_loss_spike_ratio` x trailing-median rolls params/slots/rng
     back to the rolling in-memory last-good snapshot and skips the batch;
     `DivergedError` after `FLAGS_guard_max_bad_steps` consecutive bad steps.
  4. cross-rank desync detection — every `FLAGS_guard_desync_interval` good
     steps the addressable-shard parameter fingerprint is all-gathered
     through the rendezvous store and voted on (`guard/desync.py`).

Every recovery is observable: `guard.steps`, `guard.bad_steps`,
`guard.rollbacks`, `guard.snapshots`, `guard.checkpoints`, `guard.stalls`,
`guard.step_errors`, `guard.preempts`, `guard.resumes`,
`guard.desync_checks`, `guard.desync_errors` monitor counters. Chaos sites:
`guard.step` (inside the supervised step — `delay` wedges it, `error`
crashes it) and `guard.snapshot` / `guard.snapshot.write` (checkpoint
commit crash / torn payload).
"""
from __future__ import annotations

import signal as _signal
import statistics
import time
from typing import Dict, Optional, Tuple

import numpy as np

from .. import faults as _faults
from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from ..core import flags as _flags
from ..core import random as _rnd
from .checkpoint import has_guard_state, load_guard_state, save_guard_state
from .errors import DivergedError, GuardError, PreemptedError
from .desync import DesyncDetector
from .watchdog import StepWatchdog


class GuardConfig:
    """Knobs, seeded from FLAGS_guard_* and overridable per-field."""

    def __init__(self, step_timeout_s: float = 0.0, warmup_steps: int = 5,
                 timeout_factor: float = 10.0, min_timeout_s: float = 30.0,
                 loss_spike_ratio: float = 10.0, snapshot_interval: int = 25,
                 max_bad_steps: int = 3, desync_interval: int = 0,
                 desync_timeout_s: float = 30.0):
        self.step_timeout_s = float(step_timeout_s)
        self.warmup_steps = int(warmup_steps)
        self.timeout_factor = float(timeout_factor)
        self.min_timeout_s = float(min_timeout_s)
        self.loss_spike_ratio = float(loss_spike_ratio)
        self.snapshot_interval = int(snapshot_interval)
        self.max_bad_steps = int(max_bad_steps)
        self.desync_interval = int(desync_interval)
        self.desync_timeout_s = float(desync_timeout_s)

    @classmethod
    def from_flags(cls, **overrides) -> "GuardConfig":
        cfg = cls(
            step_timeout_s=_flags.flag("guard_step_timeout_s"),
            warmup_steps=_flags.flag("guard_warmup_steps"),
            timeout_factor=_flags.flag("guard_timeout_factor"),
            min_timeout_s=_flags.flag("guard_min_timeout_s"),
            loss_spike_ratio=_flags.flag("guard_loss_spike_ratio"),
            snapshot_interval=_flags.flag("guard_snapshot_interval"),
            max_bad_steps=_flags.flag("guard_max_bad_steps"),
            desync_interval=_flags.flag("guard_desync_interval"),
            desync_timeout_s=_flags.flag("guard_desync_timeout_s"))
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise TypeError(f"GuardConfig has no knob {k!r}")
            setattr(cfg, k, v)
        return cfg


_PREEMPT_SIGNALS = (_signal.SIGTERM, _signal.SIGINT)


class TrainGuard:
    """Supervises one training loop. Use as a context manager so signal
    handlers and the watchdog runner are always torn down:

        step = TrainStep(model, loss_fn, opt)
        with TrainGuard(step, ckpt_dir="ckpt/guard") as guard:
            start = guard.resume() or (0, 0)
            for epoch in range(epochs):
                for b, (x, y) in enumerate(batches):
                    if (epoch, b) < start:
                        continue          # fast-forward after resume
                    guard.set_cursor(epoch, b)
                    loss = guard.step(x, y)   # None = bad step skipped
    """

    def __init__(self, step, ckpt_dir: Optional[str] = None,
                 config: Optional[GuardConfig] = None, scaler=None,
                 store=None, rank: int = 0, world_size: int = 1,
                 signals=_PREEMPT_SIGNALS):
        self._step_fn = step
        self.ckpt_dir = ckpt_dir
        self.cfg = config or GuardConfig.from_flags()
        self.scaler = scaler
        self._signals = tuple(signals)
        self._watchdog = StepWatchdog(
            timeout_s=self.cfg.step_timeout_s,
            warmup_steps=self.cfg.warmup_steps,
            factor=self.cfg.timeout_factor,
            min_timeout_s=self.cfg.min_timeout_s)
        self._store = store
        self._rank = int(rank)
        self._world_size = int(world_size)
        self._tl_round = 0
        self._detector = None
        if store is not None and world_size > 1:
            self._detector = DesyncDetector(
                store, rank, world_size,
                timeout_s=self.cfg.desync_timeout_s)
        self._snapshot = None
        self._good_losses = []
        self._consec_bad = 0
        self._good_steps = 0
        self._desync_round = 0
        self._cursor: Tuple[int, int] = (0, 0)
        self._next_cursor: Tuple[int, int] = (0, 0)
        self.resume_cursor: Optional[Tuple[int, int]] = None
        self._preempt_signum: Optional[int] = None
        self._prev_handlers: Dict[int, object] = {}
        self._closed = False

    # ---- lifecycle ----
    def __enter__(self) -> "TrainGuard":
        self.install_signal_handlers()
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def install_signal_handlers(self) -> None:
        """Main-thread only (CPython delivers signals there — which is
        also why a step running on the watchdog thread can never swallow
        one). Handlers only set a flag: the in-flight step always
        finishes before the checkpoint is cut."""
        for sig in self._signals:
            if sig not in self._prev_handlers:
                self._prev_handlers[sig] = _signal.getsignal(sig)
            _signal.signal(sig, self._on_signal)

    def restore_signal_handlers(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev_handlers = {}

    def _on_signal(self, signum, frame) -> None:
        self._preempt_signum = signum
        if _monitor._ENABLED:
            _monitor.count("guard.preempts")

    def close(self, grace_s: float = 5.0) -> None:
        if self._closed:
            return
        self._closed = True
        self.restore_signal_handlers()
        self._watchdog.close(grace_s=grace_s)

    # ---- cursor ----
    def set_cursor(self, epoch: int, batch: int) -> None:
        """Tell the guard which batch the NEXT step() consumes, so a
        preemption checkpoint knows where the DataLoader must resume."""
        self._cursor = (int(epoch), int(batch))

    # ---- the guarded step ----
    def step(self, *batch) -> Optional[float]:
        """Run one supervised train step. Returns the float loss, or None
        when the divergence guard skipped the batch (params rolled back).
        Raises StepStalledError / DivergedError / RankDesyncError /
        PreemptedError as typed failures."""
        if self._closed:
            raise RuntimeError("TrainGuard is closed")
        # one step record wraps everything the guard does for this batch —
        # the wrapped TrainStep joins it (step_record is reentrant), and
        # snapshot/desync/checkpoint overhead lands in the same record
        with _obs.step_record():
            return self._step_guarded(*batch)

    def _step_guarded(self, *batch) -> Optional[float]:
        if self._snapshot is None:
            self._maybe_first_snapshot()
        watchdog = self._watchdog

        def supervised():
            watchdog.phase("dispatch")
            if _faults._ENABLED:
                _faults.check("guard.step")
            loss_t = self._step_fn(*batch)
            watchdog.phase("host-sync")
            return float(np.asarray(getattr(loss_t, "_value", loss_t)))

        bad_reason = None
        loss = None
        try:
            loss = watchdog.run(supervised)
        except FloatingPointError as e:
            # traced FLAGS_check_nan_inf raise: state was committed (the
            # donated buffers demanded it) but is poisoned — roll back
            bad_reason = f"non-finite (check_nan_inf): {e}"
        except GuardError:
            raise  # stalls/desyncs already counted under their own name
        except Exception:
            if _monitor._ENABLED:
                _monitor.count("guard.step_errors")
            raise
        if bad_reason is None and loss is not None:
            if not np.isfinite(loss):
                bad_reason = f"non-finite loss {loss}"
            elif self._is_spike(loss):
                bad_reason = (f"loss spike {loss:.6g} > "
                              f"{self.cfg.loss_spike_ratio}x trailing median")
        if bad_reason is not None:
            return self._handle_bad_step(loss, bad_reason)
        # ---- good step ----
        self._consec_bad = 0
        self._good_steps += 1
        self._good_losses.append(loss)
        if len(self._good_losses) > 64:
            del self._good_losses[:-64]
        if _monitor._ENABLED:
            _monitor.count("guard.steps")
        self._next_cursor = (self._cursor[0], self._cursor[1] + 1)
        if self.cfg.snapshot_interval > 0 and \
                self._good_steps % self.cfg.snapshot_interval == 0:
            self._take_snapshot()
        if self._detector is not None and self.cfg.desync_interval > 0 and \
                self._good_steps % self.cfg.desync_interval == 0:
            self._desync_round += 1
            with _obs.phase("desync"):
                self._detector.check(self._desync_round,
                                     self._step_fn.named_param_arrays())
        if self._preempt_signum is not None:
            signum = self._preempt_signum
            self._preempt_signum = None
            if self.ckpt_dir:
                self.checkpoint()
            err = PreemptedError(signum, self.ckpt_dir, self._next_cursor)
            if _obs._FR_ENABLED:
                # SIGTERM black box: the dump records the last steps and
                # where the preempted run stood, next to the checkpoint
                _obs.record_event("guard.preempt", signum=signum,
                                  ckpt_dir=self.ckpt_dir,
                                  cursor=list(self._next_cursor))
                _obs.dump_on_error(err)
            raise err
        return loss

    def _is_spike(self, loss: float) -> bool:
        if self.cfg.loss_spike_ratio <= 0 or len(self._good_losses) < 3:
            return False
        med = statistics.median(self._good_losses)
        if med <= 0:  # spike heuristic only meaningful for positive losses
            return False
        return loss > self.cfg.loss_spike_ratio * med

    def _handle_bad_step(self, loss, reason: str) -> None:
        self._consec_bad += 1
        if _monitor._ENABLED:
            _monitor.count("guard.bad_steps")
        if _obs._FR_ENABLED:
            _obs.record_event("guard.bad_step", reason=reason,
                              consec_bad=self._consec_bad,
                              step=self._good_steps + 1)
        self._rollback()
        if self._consec_bad >= max(1, self.cfg.max_bad_steps):
            err = DivergedError(bad_steps=self._consec_bad, last_loss=loss,
                                step=self._good_steps + 1)
            if _obs._FR_ENABLED:
                _obs.dump_on_error(err)
            raise err
        return None

    # ---- rolling in-memory snapshot / rollback ----
    def _maybe_first_snapshot(self) -> None:
        """A last-good snapshot must exist before the first bad step.
        jit.TrainStep can build (and thus snapshot) without a batch;
        SPMDTrainStep cannot — its first snapshot lands after step 1."""
        try:
            self._take_snapshot()
        except RuntimeError:
            pass

    def _take_snapshot(self) -> None:
        with _obs.phase("snapshot"):
            snap = {"step": self._step_fn.state_dict(),
                    "rng": _rnd.get_rng_state()}
            if self.scaler is not None:
                snap["scaler"] = self.scaler.state_dict()
            self._snapshot = snap
        if _mem._ENABLED:
            # snapshot boundaries are the census cadence of a guarded run:
            # the host copy just doubled transient footprint, and the ring
            # of these records is what the leak watch differences
            _mem.census()
        if _monitor._ENABLED:
            _monitor.count("guard.snapshots")

    def _rollback(self) -> None:
        if self._snapshot is None:
            return
        self._step_fn.set_state_dict(self._snapshot["step"])
        _rnd.set_rng_state(self._snapshot["rng"])
        if self.scaler is not None and "scaler" in self._snapshot:
            self.scaler.load_state_dict(self._snapshot["scaler"])
        if _monitor._ENABLED:
            _monitor.count("guard.rollbacks")
        if _obs._FR_ENABLED:
            _obs.record_event("guard.rollback", step=self._good_steps + 1)

    # ---- durable checkpoint / resume ----
    def _lr_scheduler(self):
        opt = getattr(self._step_fn, "optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "state_dict") else None

    def checkpoint(self) -> str:
        """Commit the FULL loop state crash-atomically to ckpt_dir."""
        if not self.ckpt_dir:
            raise ValueError("TrainGuard has no ckpt_dir configured")
        with _obs.phase("checkpoint"):
            return self._checkpoint_impl()

    def _checkpoint_impl(self) -> str:
        sd = self._step_fn.state_dict()
        arrays: Dict[str, np.ndarray] = {}
        for n, v in sd["params"].items():
            arrays[f"params/{n}"] = v
        for i, s in enumerate(sd["slots"]):
            for k, v in s.items():
                arrays[f"slots/{i}/{k}"] = v
        if "rng_key" in sd:
            arrays["step/rng_key"] = sd["rng_key"]
        if "t" in sd:
            arrays["step/t"] = sd["t"]
        seed, count, kd, pool = _rnd.get_rng_state()
        if kd is not None:
            arrays["grng/key"] = np.asarray(kd)
        for i, p in enumerate(pool):
            arrays[f"grng/pool/{i}"] = np.asarray(p)
        meta = {
            "kind": sd["kind"],
            "step_count": sd["step_count"],
            "cursor": list(self._next_cursor),
            "good_steps": self._good_steps,
            "good_losses": [float(x) for x in self._good_losses[-16:]],
            "grng": {"seed": int(seed), "count": int(count),
                     "pool_len": len(pool), "has_key": kd is not None},
            "slot_keys": [sorted(s) for s in sd["slots"]],
            "param_names": sorted(sd["params"]),
            "wallclock": time.time(),
        }
        if self.scaler is not None:
            meta["scaler"] = {k: float(v) if isinstance(v, float) else v
                              for k, v in self.scaler.state_dict().items()}
        sched = self._lr_scheduler()
        if sched is not None:
            meta["lr_scheduler"] = sched.state_dict()
        save_guard_state(self.ckpt_dir, arrays, meta)
        return self.ckpt_dir

    def resume(self) -> Optional[Tuple[int, int]]:
        """Restore the loop from the newest intact guard checkpoint.
        Returns the (epoch, batch) cursor the loop must fast-forward to,
        or None when no checkpoint exists (fresh start)."""
        if not self.ckpt_dir or not has_guard_state(self.ckpt_dir):
            return None
        arrays, meta = load_guard_state(self.ckpt_dir)
        params = {n: arrays[f"params/{n}"] for n in meta["param_names"]}
        slots = [{k: arrays[f"slots/{i}/{k}"] for k in keys}
                 for i, keys in enumerate(meta["slot_keys"])]
        sd = {"kind": meta["kind"], "params": params, "slots": slots,
              "step_count": meta["step_count"]}
        if "step/rng_key" in arrays:
            sd["rng_key"] = arrays["step/rng_key"]
        if "step/t" in arrays:
            sd["t"] = arrays["step/t"]
        self._step_fn.set_state_dict(sd)
        g = meta["grng"]
        kd = arrays.get("grng/key") if g.get("has_key") else None
        pool = tuple(arrays[f"grng/pool/{i}"] for i in range(g["pool_len"]))
        _rnd.set_rng_state((g["seed"], g["count"], kd, pool))
        if self.scaler is not None and "scaler" in meta:
            self.scaler.load_state_dict(meta["scaler"])
        sched = self._lr_scheduler()
        if sched is not None and "lr_scheduler" in meta:
            sched.set_state_dict(meta["lr_scheduler"])
        self._good_steps = int(meta.get("good_steps", 0))
        self._good_losses = [float(x) for x in meta.get("good_losses", [])]
        self._consec_bad = 0
        self._snapshot = None
        self.resume_cursor = tuple(meta["cursor"])
        if _monitor._ENABLED:
            _monitor.count("guard.resumes")
        if _obs._FR_ENABLED:
            _obs.record_event("guard.resume", ckpt_dir=self.ckpt_dir,
                              cursor=list(self.resume_cursor))
        return self.resume_cursor

    # ---- pod timeline (obs cross-rank merge) ----
    def timeline_report(self, timeout_s: Optional[float] = None):
        """Merge every rank's step timeline into one pod timeline and name
        the straggler rank per phase. Multi-rank (a rendezvous store was
        passed): all ranks MUST call this collectively — records are
        exchanged through the store like desync fingerprints. Single rank:
        a local merge of this process's timeline. Returns
        (merged_dict, report_str); timeline disabled -> (None, explanation).
        """
        if not _obs._TL_ENABLED:
            return None, ("step timeline disabled — set "
                          "FLAGS_obs_timeline=1 to record phases")
        records = _obs.timeline().records()
        if self._store is not None and self._world_size > 1:
            self._tl_round += 1
            per_rank = _obs.gather_timelines(
                self._store, self._rank, self._world_size, records,
                key=f"obs/tl/{self._tl_round}",
                timeout_s=timeout_s if timeout_s is not None
                else self.cfg.desync_timeout_s)
        else:
            per_rank = {self._rank: _obs.slim_records(records)}
        merged = _obs.merge_timelines(per_rank)
        return merged, _obs.straggler_report(merged)
