"""paddle_tpu.guard — the training guard plane.

PR 3 made the distributed *substrate* survive faults; this package makes
the training *loop* survive them: preemption-safe auto-resume (SIGTERM →
finish the in-flight step → crash-atomic full-loop-state checkpoint →
bit-identical `resume()`), a step watchdog (hung step/collective →
`StepStalledError` with the last-known phase), a divergence guard
(non-finite/spiking loss → rollback to the rolling last-good snapshot +
skip, `DivergedError` after `FLAGS_guard_max_bad_steps`), and cross-rank
desync detection (parameter-fingerprint vote over the data-parallel
group → `RankDesyncError` naming the offender).

Reference parity: PaddlePaddle's `FLAGS_check_nan_inf`
(`details/nan_inf_utils_detail.cc`) is the divergence half; fleet's
elastic + auto-checkpoint roles are the resume half; the
last-good-generation + restore-exact-state discipline (JAX/Orbax style)
is the model for resume semantics.
"""
from .errors import (DivergedError, GuardError, PreemptedError,  # noqa: F401
                     RankDesyncError, StepStalledError)
from .watchdog import StepWatchdog  # noqa: F401
from .desync import DesyncDetector, array_crc, fingerprint  # noqa: F401
from .checkpoint import (guard_state_version, has_guard_state,  # noqa: F401
                         load_guard_state, rollback_guard_state,
                         save_guard_state)
from .supervisor import GuardConfig, TrainGuard  # noqa: F401

__all__ = [
    "GuardError", "PreemptedError", "StepStalledError", "DivergedError",
    "RankDesyncError",
    "GuardConfig", "TrainGuard", "StepWatchdog", "DesyncDetector",
    "fingerprint", "array_crc",
    "save_guard_state", "load_guard_state", "has_guard_state",
    "rollback_guard_state", "guard_state_version",
]
