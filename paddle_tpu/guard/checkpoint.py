"""Crash-atomic loop-state checkpoints for the training guard.

Same commit discipline as `framework/sharded_io.py` (whose atomic_write /
CRC helpers this module reuses): the array payload is written under a NEW
versioned name via tmp+fsync+rename, then the manifest — the commit record
carrying the payload name, its whole-file CRC32 and the per-array dtype map
— atomically replaces the previous one. A SIGKILL at any point leaves the
previous manifest pointing at its intact payload; the previous generation
is kept as `guard-meta.json.bak` and is the corruption fallback on load.

Fault sites: `guard.snapshot.write` (torn-payload mangle) and
`guard.snapshot` (deterministic crash point between payload and commit)
drive the chaos tests.

The payload is a flat name->ndarray npz; extension dtypes (bfloat16,
float8_*) round-trip via the manifest dtype map + `.view()` exactly like
`load_sharded` (npz stores them as raw void bytes).
"""
from __future__ import annotations

import io
import json
import os
import shutil
from typing import Dict, Tuple

import numpy as np

from .. import faults as _faults
from .. import monitor as _monitor
from ..framework.sharded_io import (CheckpointCorruptError, _crc, _np_dtype,
                                    atomic_write)

_META = "guard-meta.json"


def save_guard_state(dirname: str, arrays: Dict[str, np.ndarray],
                     meta: dict) -> str:
    """Commit one loop-state generation; returns the payload path."""
    os.makedirs(dirname, exist_ok=True)
    mpath = os.path.join(dirname, _META)
    prev = _read_meta(mpath)
    version = int(prev.get("version", 0)) + 1 if prev else 1
    buf = io.BytesIO()
    np.savez(buf, **{k: np.ascontiguousarray(np.asarray(v))
                     for k, v in arrays.items()})
    data = buf.getvalue()
    state_file = f"guard-state-v{version}.npz"
    record = {"version": version, "state_file": state_file,
              "file_crc": _crc(data),  # of the INTENDED bytes: a torn
              "dtypes": {k: str(np.asarray(v).dtype)  # write must fail load
                         for k, v in arrays.items()},
              "meta": meta}
    if _faults._ENABLED:
        data = _faults.mangle("guard.snapshot.write", data)
    atomic_write(os.path.join(dirname, state_file), data)
    if _faults._ENABLED:
        # deterministic crash point BETWEEN payload and commit: the meta
        # still references the previous generation
        _faults.check("guard.snapshot")
    if os.path.exists(mpath):  # keep one fallback generation
        shutil.copyfile(mpath, mpath + ".bak")
    atomic_write(mpath, json.dumps(record).encode())
    _gc(dirname, keep={state_file, prev.get("state_file", "")})
    if _monitor._ENABLED:
        _monitor.count("guard.checkpoints")
    return os.path.join(dirname, state_file)


def _read_meta(mpath: str) -> dict:
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _gc(dirname: str, keep) -> None:
    import glob
    for path in glob.glob(os.path.join(dirname, "guard-state-v*.npz")):
        if os.path.basename(path) not in keep:
            try:
                os.remove(path)
            except OSError:
                pass


def _load_one(dirname: str, mpath: str) -> Tuple[Dict[str, np.ndarray], dict]:
    record = _read_meta(mpath)
    if not record:
        raise CheckpointCorruptError(f"unreadable guard manifest {mpath}")
    path = os.path.join(dirname, record.get("state_file", ""))
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise CheckpointCorruptError(f"missing guard state file {path}") from e
    if "file_crc" in record and _crc(raw) != record["file_crc"]:
        raise CheckpointCorruptError(
            f"guard state file {path} failed its checksum (torn/corrupt)")
    try:
        npz = np.load(io.BytesIO(raw))
        dtypes = record.get("dtypes", {})
        arrays = {}
        for key in npz.files:
            arr = npz[key]
            want = _np_dtype(dtypes[key]) if key in dtypes else arr.dtype
            if arr.dtype != want:  # extension dtypes stored as void bytes
                arr = np.ascontiguousarray(arr).view(want)
            arrays[key] = arr
    except Exception as e:
        raise CheckpointCorruptError(
            f"guard state file {path} is unreadable: {e}") from e
    return arrays, record.get("meta", {})


def load_guard_state(dirname: str) -> Tuple[Dict[str, np.ndarray], dict]:
    """Load the newest intact generation (arrays, meta). Falls back to the
    previous committed generation on corruption (counting
    `guard.ckpt_fallbacks`); raises FileNotFoundError when no checkpoint
    was ever committed, CheckpointCorruptError when none is intact."""
    mpath = os.path.join(dirname, _META)
    if not os.path.exists(mpath) and not os.path.exists(mpath + ".bak"):
        raise FileNotFoundError(f"no guard checkpoint in {dirname}")
    try:
        return _load_one(dirname, mpath)
    except CheckpointCorruptError as e:
        bak = mpath + ".bak"
        if not os.path.exists(bak):
            raise
        if _monitor._ENABLED:
            _monitor.count("guard.ckpt_fallbacks")
        import warnings
        warnings.warn(f"guard checkpoint: {e}; falling back to the previous "
                      f"committed generation ({bak})")
        return _load_one(dirname, bak)


def has_guard_state(dirname: str) -> bool:
    mpath = os.path.join(dirname, _META)
    return os.path.exists(mpath) or os.path.exists(mpath + ".bak")


def guard_state_version(dirname: str) -> int:
    """Version of the current committed generation (0 = none)."""
    return int(_read_meta(os.path.join(dirname, _META)).get("version", 0))


def rollback_guard_state(dirname: str) -> int:
    """INSTANT rollback: promote the `.bak` fallback generation to
    current (the fleet tier's bad-model-push escape hatch — the previous
    generation's payload is still on disk because `_gc` always keeps it).
    The fallback is CRC-verified BEFORE promotion; returns the restored
    version. Raises CheckpointCorruptError when there is no intact
    fallback to roll back to."""
    mpath = os.path.join(dirname, _META)
    bak = mpath + ".bak"
    if not os.path.exists(bak):
        raise CheckpointCorruptError(
            f"no fallback generation to roll back to in {dirname}")
    _load_one(dirname, bak)  # verify intact before promoting
    record = _read_meta(bak)
    atomic_write(mpath, json.dumps(record).encode())
    if _monitor._ENABLED:
        _monitor.count("guard.ckpt_rollbacks")
    return int(record.get("version", 0))
