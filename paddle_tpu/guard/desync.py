"""Cross-rank desync detection — cheap parameter fingerprints, compared
across the data-parallel group every N steps.

On a pod slice a rank can silently diverge (bit flip, dropped collective,
nondeterministic kernel) and train a *different* model for hours before
eval notices. The detector computes a CRC32 fingerprint of this process's
addressable parameter shards (per-shard CRCs folded with the parameter
name, so layout changes also show), all-gathers the 4-byte value through
the job's rendezvous store (`collective.store_all_gather_object` — the
cross-process regime), and votes: the majority fingerprint is truth, ties
break toward the lowest rank's value (rank 0 is the broadcast source of
initial params, so in a 2-rank tie the non-zero rank is named). Any
minority rank raises `RankDesyncError` naming the offender(s) on EVERY
rank — the whole group stops instead of averaging a poisoned gradient.
"""
from __future__ import annotations

import zlib
from collections import Counter
from typing import Dict, List

import numpy as np

from .. import monitor as _monitor
from .. import obs as _obs
from .errors import RankDesyncError


def array_crc(arr) -> int:
    """CRC32 of an array's addressable bytes. For a sharded jax.Array this
    folds each addressable shard in index order — every rank hashes only
    what it holds, so the check costs one D2H of local shards, never a
    gather of the full parameter."""
    if hasattr(arr, "addressable_shards"):
        crc = 0
        shards = sorted(arr.addressable_shards,
                        key=lambda s: tuple(sl.start or 0 for sl in s.index))
        for sh in shards:
            crc = zlib.crc32(
                np.ascontiguousarray(np.asarray(sh.data)).tobytes(), crc)
        return crc & 0xFFFFFFFF
    return zlib.crc32(np.ascontiguousarray(np.asarray(arr)).tobytes()) \
        & 0xFFFFFFFF


def fingerprint(named_arrays: Dict[str, object]) -> int:
    """Order-independent over insertion (names sorted), order-dependent
    over content: one 32-bit value summarizing every parameter."""
    crc = 0
    for name in sorted(named_arrays):
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(array_crc(named_arrays[name]).to_bytes(4, "little"),
                         crc)
    return crc & 0xFFFFFFFF


class DesyncDetector:
    """One detector per rank; `check(step, named_arrays)` is called by the
    guard every `FLAGS_guard_desync_interval` good steps."""

    def __init__(self, store, rank: int, world_size: int,
                 timeout_s: float = 30.0, prefix: str = "guard/fp"):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.timeout_s = float(timeout_s)
        self.prefix = prefix

    def check(self, step: int, named_arrays: Dict[str, object]) -> Dict[int, int]:
        """Exchange fingerprints for `step`; returns {rank: fingerprint} or
        raises RankDesyncError naming the minority rank(s)."""
        if self.world_size <= 1:
            return {self.rank: fingerprint(named_arrays)}
        if _monitor._ENABLED:
            _monitor.count("guard.desync_checks")
        from ..parallel.collective import store_all_gather_object
        fp = fingerprint(named_arrays)
        fps = store_all_gather_object(
            self.store, f"{self.prefix}/{step}", fp,
            self.rank, self.world_size, timeout_s=self.timeout_s)
        fps = {int(r): int(v) for r, v in fps.items()}
        offenders = self._vote(fps)
        if offenders:
            if _monitor._ENABLED:
                _monitor.count("guard.desync_errors")
            err = RankDesyncError(step=step, offenders=offenders,
                                  fingerprints=fps)
            if _obs._FR_ENABLED:
                _obs.record_event("guard.desync", step=step,
                                  offenders=offenders,
                                  fingerprints={str(r): v
                                                for r, v in fps.items()})
                _obs.dump_on_error(err)
            raise err
        return fps

    @staticmethod
    def _vote(fps: Dict[int, int]) -> List[int]:
        counts = Counter(fps.values())
        maxc = max(counts.values())
        tied = {v for v, c in counts.items() if c == maxc}
        ref = fps[min(r for r in fps if fps[r] in tied)]
        return sorted(r for r, v in fps.items() if v != ref)
