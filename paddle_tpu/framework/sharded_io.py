"""Sharded + auto checkpointing.

Reference parity: sharded checkpoint flows (`dist_sharding_save.py`,
`auto_parallel_save_load.py` test patterns — each rank saves its parameter
shard) and elastic auto-checkpoint
(`fluid/incubate/checkpoint/auto_checkpoint.py:71` — `train_epoch_range`
wraps the loop, snapshotting state every epoch so a relaunched job
resumes where it died).

TPU-native: a sharded save asks each ADDRESSABLE shard of a GSPMD array
for its data and writes one npz per host plus a JSON manifest (single-host
multi-device writes one file); load re-places shards onto the mesh with
`jax.device_put` per NamedSharding. Auto-checkpoint keys snapshots by an
epoch counter in the checkpoint dir; `train_epoch_range` skips completed
epochs on restart — the relaunch loop (elastic.launch_elastic) plus this
gives kill-and-resume.
"""
from __future__ import annotations

import json
import os
from typing import Callable, Dict, Iterator, Optional

import numpy as np
import jax


def _np_dtype(name: str) -> np.dtype:
    """np.dtype from a manifest string, resolving extension dtypes
    (bfloat16, float8_*) through ml_dtypes — np.dtype('bfloat16') alone
    raises TypeError on stock numpy."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def save_sharded(state: Dict[str, object], dirname: str,
                 process_index: Optional[int] = None):
    """Write this process's addressable shards of every array in `state`
    (values: jax arrays / Tensors / numpy). Layout:
    dirname/manifest.json + dirname/shards-p<proc>.npz"""
    os.makedirs(dirname, exist_ok=True)
    proc = jax.process_index() if process_index is None else process_index
    manifest = {"arrays": {}, "process_count": jax.process_count()}
    blobs = {}
    for name, v in state.items():
        arr = getattr(v, "_value", v)
        arr = arr if isinstance(arr, jax.Array) else np.asarray(arr)
        manifest["arrays"][name] = {"shape": list(np.shape(arr)),
                                    "dtype": str(np.asarray(arr).dtype
                                                 if not isinstance(arr, jax.Array)
                                                 else arr.dtype)}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            for sh in arr.addressable_shards:
                key = f"{name}::{'_'.join(str(s.start or 0) for s in sh.index)}"
                blobs[key] = np.asarray(sh.data)
                manifest["arrays"][name].setdefault("shards", []).append(
                    {"key": key,
                     "index": [[s.start or 0, s.stop] for s in sh.index]})
        else:
            blobs[f"{name}::full"] = np.asarray(arr)
            manifest["arrays"][name]["shards"] = [
                {"key": f"{name}::full", "index": None}]
    np.savez(os.path.join(dirname, f"shards-p{proc}.npz"), **blobs)
    with open(os.path.join(dirname, f"manifest-p{proc}.json"), "w") as f:
        json.dump(manifest, f)


def load_sharded(dirname: str, shardings: Optional[Dict] = None,
                 ) -> Dict[str, np.ndarray]:
    """Reassemble arrays from every process's shard files; if `shardings`
    maps name -> jax Sharding, arrays are device_put with it."""
    import glob
    arrays: Dict[str, np.ndarray] = {}
    manifests = sorted(glob.glob(os.path.join(dirname, "manifest-p*.json")))
    if not manifests:
        raise FileNotFoundError(f"no sharded checkpoint in {dirname}")
    for mpath in manifests:
        with open(mpath) as f:
            manifest = json.load(f)
        proc = os.path.basename(mpath)[len("manifest-p"):-len(".json")]
        blobs = np.load(os.path.join(dirname, f"shards-p{proc}.npz"))
        for name, meta in manifest["arrays"].items():
            want = _np_dtype(meta["dtype"])
            if name not in arrays:
                arrays[name] = np.zeros(meta["shape"], want)
            for sh in meta.get("shards", []):
                data = blobs[sh["key"]]
                if data.dtype != want:
                    # npz stores ml_dtypes (bfloat16, …) as raw void bytes
                    # ('|V2'); re-view with the manifest dtype.
                    data = np.ascontiguousarray(data).view(want)
                if sh["index"] is None:
                    arrays[name] = data
                else:
                    idx = tuple(slice(a, b) for a, b in sh["index"])
                    arrays[name][idx] = data
    if shardings:
        for name, sharding in shardings.items():
            if name in arrays:
                arrays[name] = jax.device_put(arrays[name], sharding)
    return arrays


class AutoCheckpoint:
    """Epoch-granular snapshot/resume (auto_checkpoint.py:71 role)."""

    def __init__(self, dirname: str, save_fn: Callable[[str], None],
                 load_fn: Callable[[str], None]):
        self.dirname = dirname
        self.save_fn = save_fn
        self.load_fn = load_fn
        os.makedirs(dirname, exist_ok=True)

    def _status_path(self):
        return os.path.join(self.dirname, "status.json")

    def _status(self) -> dict:
        try:
            with open(self._status_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def completed_epochs(self) -> int:
        return int(self._status().get("epoch", 0))

    def train_epoch_range(self, max_epochs: int) -> Iterator[int]:
        """for epoch in acp.train_epoch_range(n): ... — on a fresh start
        yields 0..n-1; after a crash/relaunch it restores the snapshot and
        resumes from the first incomplete epoch.

        Crash-safety: each epoch writes a VERSIONED snapshot, then commits
        status (snapshot path + epoch) atomically via os.replace. A kill
        between the snapshot write and the commit leaves status pointing at
        the previous intact snapshot, so the interrupted epoch replays
        exactly once — never double-applies."""
        st = self._status()
        start = int(st.get("epoch", 0))
        if start > 0:
            self.load_fn(st.get("snapshot",
                                os.path.join(self.dirname, "snapshot")))
        for epoch in range(start, max_epochs):
            yield epoch
            snap = os.path.join(self.dirname, f"snapshot-{epoch + 1}")
            self.save_fn(snap)
            tmp = self._status_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"epoch": epoch + 1, "snapshot": snap}, f)
            os.replace(tmp, self._status_path())  # atomic commit
            prev = os.path.join(self.dirname, f"snapshot-{epoch}")
            if os.path.isdir(prev):
                import shutil
                shutil.rmtree(prev, ignore_errors=True)
            elif os.path.isfile(prev):  # save_fn may write one file per snap
                try:
                    os.remove(prev)
                except OSError:
                    pass
