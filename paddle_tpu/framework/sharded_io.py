"""Sharded + auto checkpointing.

Reference parity: sharded checkpoint flows (`dist_sharding_save.py`,
`auto_parallel_save_load.py` test patterns — each rank saves its parameter
shard) and elastic auto-checkpoint
(`fluid/incubate/checkpoint/auto_checkpoint.py:71` — `train_epoch_range`
wraps the loop, snapshotting state every epoch so a relaunched job
resumes where it died).

TPU-native: a sharded save asks each ADDRESSABLE shard of a GSPMD array
for its data and writes one npz per host plus a JSON manifest (single-host
multi-device writes one file); load re-places shards onto the mesh with
`jax.device_put` per NamedSharding. Auto-checkpoint keys snapshots by an
epoch counter in the checkpoint dir; `train_epoch_range` skips completed
epochs on restart — the relaunch loop (elastic.launch_elastic) plus this
gives kill-and-resume.
"""
from __future__ import annotations

import io
import json
import os
import zlib
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np
import jax

from .. import faults as _faults
from .. import monitor as _monitor


class CheckpointCorruptError(RuntimeError):
    """Checkpoint data failed checksum/structure verification and no
    intact fallback generation exists."""


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def atomic_write(path: str, data: bytes, unique_tmp: bool = False) -> None:
    """tmp + fsync + rename: the final name either holds the complete
    bytes or does not exist — a crash mid-write can never leave a
    half-written file under the committed name. Shared by the sharded
    checkpoint writer, `framework.io.save` (so `hapi.ModelCheckpoint`
    can never leave a torn `.pdparams` behind a SIGKILL), the guard
    plane's loop-state checkpoints (`paddle_tpu.guard.checkpoint`), and
    the persistent compile cache (`core/compile_cache.py`).

    unique_tmp=True gives each writer its own tmp name (pid + thread id)
    so CONCURRENT lock-free writers to the same committed name cannot
    interleave inside one tmp file — whoever renames last wins, and both
    candidate files were complete (the compile-cache write-race
    contract)."""
    if unique_tmp:
        import threading
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    else:
        tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


_atomic_write = atomic_write  # internal alias (pre-guard name)


def _np_dtype(name: str) -> np.dtype:
    """np.dtype from a manifest string, resolving extension dtypes
    (bfloat16, float8_*) through ml_dtypes — np.dtype('bfloat16') alone
    raises TypeError on stock numpy."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def save_sharded(state: Dict[str, object], dirname: str,
                 process_index: Optional[int] = None):
    """Write this process's addressable shards of every array in `state`
    (values: jax arrays / Tensors / numpy). Layout:
    dirname/manifest-p<proc>.json + dirname/shards-p<proc>-v<N>.npz

    Crash-atomic commit protocol: the shard file is written under a NEW
    versioned name (tmp + fsync + rename), then the manifest — the commit
    record, carrying the shard file name plus whole-file and per-shard
    CRC32 checksums — atomically replaces the previous one. A crash at
    any point leaves the previous manifest pointing at its intact shard
    file, so `load_sharded` always finds a complete snapshot. The
    previous generation is kept as `manifest-p<proc>.json.bak` (+ its
    shard file) and is the corruption fallback; older generations are
    garbage-collected after a successful commit."""
    os.makedirs(dirname, exist_ok=True)
    proc = jax.process_index() if process_index is None else process_index
    mpath = os.path.join(dirname, f"manifest-p{proc}.json")
    prev = _read_manifest(mpath)
    version = int(prev.get("version", 0)) + 1 if prev else 1
    manifest = {"arrays": {}, "process_count": jax.process_count(),
                "version": version}
    blobs = {}
    for name, v in state.items():
        arr = getattr(v, "_value", v)
        arr = arr if isinstance(arr, jax.Array) else np.asarray(arr)
        manifest["arrays"][name] = {"shape": list(np.shape(arr)),
                                    "dtype": str(np.asarray(arr).dtype
                                                 if not isinstance(arr, jax.Array)
                                                 else arr.dtype)}
        if isinstance(arr, jax.Array) and hasattr(arr, "addressable_shards"):
            for sh in arr.addressable_shards:
                key = f"{name}::{'_'.join(str(s.start or 0) for s in sh.index)}"
                blobs[key] = np.asarray(sh.data)
                manifest["arrays"][name].setdefault("shards", []).append(
                    {"key": key,
                     "index": [[s.start or 0, s.stop] for s in sh.index],
                     "crc": _crc(blobs[key].tobytes())})
        else:
            blobs[f"{name}::full"] = np.asarray(arr)
            manifest["arrays"][name]["shards"] = [
                {"key": f"{name}::full", "index": None,
                 "crc": _crc(blobs[f"{name}::full"].tobytes())}]
    buf = io.BytesIO()
    np.savez(buf, **blobs)
    data = buf.getvalue()
    shard_file = f"shards-p{proc}-v{version}.npz"
    manifest["shard_file"] = shard_file
    manifest["file_crc"] = _crc(data)   # of the INTENDED bytes: a torn
    if _faults._ENABLED:                # write below must fail the check
        data = _faults.mangle("ckpt.write", data)
    _atomic_write(os.path.join(dirname, shard_file), data)
    if _faults._ENABLED:
        # deterministic crash point BETWEEN data and commit: the manifest
        # still references the previous generation
        _faults.check("ckpt.commit")
    if os.path.exists(mpath):           # keep one fallback generation
        import shutil
        shutil.copyfile(mpath, mpath + ".bak")
    _atomic_write(mpath, json.dumps(manifest).encode())
    _gc_shard_files(dirname, proc, keep={shard_file,
                                         prev.get("shard_file", "")})


def _read_manifest(mpath: str) -> dict:
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _gc_shard_files(dirname: str, proc, keep) -> None:
    import glob
    for path in glob.glob(os.path.join(dirname, f"shards-p{proc}-v*.npz")):
        if os.path.basename(path) not in keep:
            try:
                os.remove(path)
            except OSError:
                pass


def _load_verified(dirname: str, mpath: str) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Load one manifest + its shard file, verifying the whole-file CRC
    and every per-shard CRC; any mismatch/unreadability raises
    CheckpointCorruptError. Legacy (pre-checksum) manifests load
    unverified."""
    manifest = _read_manifest(mpath)
    if not manifest:
        raise CheckpointCorruptError(f"unreadable manifest {mpath}")
    proc = os.path.basename(mpath)[len("manifest-p"):].split(".", 1)[0]
    fname = manifest.get("shard_file", f"shards-p{proc}.npz")
    path = os.path.join(dirname, fname)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise CheckpointCorruptError(
            f"missing shard file {path}") from e
    if "file_crc" in manifest and _crc(raw) != manifest["file_crc"]:
        raise CheckpointCorruptError(
            f"shard file {path} failed its checksum (torn/corrupt write)")
    try:
        npz = np.load(io.BytesIO(raw))
        blobs = {}
        for name, meta in manifest["arrays"].items():
            for sh in meta.get("shards", []):
                blob = npz[sh["key"]]
                if "crc" in sh and _crc(
                        np.ascontiguousarray(blob).tobytes()) != sh["crc"]:
                    raise CheckpointCorruptError(
                        f"shard {sh['key']} in {path} failed its checksum")
                blobs[sh["key"]] = blob
    except CheckpointCorruptError:
        raise
    except Exception as e:   # zip/pickle/KeyError-level damage
        raise CheckpointCorruptError(
            f"shard file {path} is unreadable: {e}") from e
    return manifest, blobs


def load_sharded(dirname: str, shardings: Optional[Dict] = None,
                 ) -> Dict[str, np.ndarray]:
    """Reassemble arrays from every process's shard files; if `shardings`
    maps name -> jax Sharding, arrays are device_put with it.

    Every shard file is checksum-verified against its manifest; on
    corruption (torn write, bit rot) the loader falls back to the
    previous committed generation (`manifest-p*.json.bak`, kept by
    `save_sharded`), counting `ckpt.fallbacks` — only when no generation
    is intact does it raise CheckpointCorruptError."""
    import glob
    arrays: Dict[str, np.ndarray] = {}
    manifests = sorted(glob.glob(os.path.join(dirname, "manifest-p*.json")))
    if not manifests:
        raise FileNotFoundError(f"no sharded checkpoint in {dirname}")
    for mpath in manifests:
        try:
            manifest, blobs = _load_verified(dirname, mpath)
        except CheckpointCorruptError as e:
            bak = mpath + ".bak"
            if not os.path.exists(bak):
                raise
            if _monitor._ENABLED:
                _monitor.count("ckpt.fallbacks")
            import warnings
            warnings.warn(f"sharded checkpoint: {e}; falling back to the "
                          f"previous committed generation ({bak})")
            manifest, blobs = _load_verified(dirname, bak)
        for name, meta in manifest["arrays"].items():
            want = _np_dtype(meta["dtype"])
            if name not in arrays:
                arrays[name] = np.zeros(meta["shape"], want)
            for sh in meta.get("shards", []):
                data = blobs[sh["key"]]
                if data.dtype != want:
                    # npz stores ml_dtypes (bfloat16, …) as raw void bytes
                    # ('|V2'); re-view with the manifest dtype.
                    data = np.ascontiguousarray(data).view(want)
                if sh["index"] is None:
                    arrays[name] = data
                else:
                    idx = tuple(slice(a, b) for a, b in sh["index"])
                    arrays[name][idx] = data
    if shardings:
        for name, sharding in shardings.items():
            if name in arrays:
                arrays[name] = jax.device_put(arrays[name], sharding)
    return arrays


class AutoCheckpoint:
    """Epoch-granular snapshot/resume (auto_checkpoint.py:71 role)."""

    def __init__(self, dirname: str, save_fn: Callable[[str], None],
                 load_fn: Callable[[str], None]):
        self.dirname = dirname
        self.save_fn = save_fn
        self.load_fn = load_fn
        os.makedirs(dirname, exist_ok=True)

    def _status_path(self):
        return os.path.join(self.dirname, "status.json")

    def _status(self) -> dict:
        try:
            with open(self._status_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def completed_epochs(self) -> int:
        return int(self._status().get("epoch", 0))

    def train_epoch_range(self, max_epochs: int) -> Iterator[int]:
        """for epoch in acp.train_epoch_range(n): ... — on a fresh start
        yields 0..n-1; after a crash/relaunch it restores the snapshot and
        resumes from the first incomplete epoch.

        Crash-safety: each epoch writes a VERSIONED snapshot, then commits
        status (snapshot path + epoch) atomically via os.replace. A kill
        between the snapshot write and the commit leaves status pointing at
        the previous intact snapshot, so the interrupted epoch replays
        exactly once — never double-applies."""
        st = self._status()
        start = int(st.get("epoch", 0))
        if start > 0:
            self.load_fn(st.get("snapshot",
                                os.path.join(self.dirname, "snapshot")))
        for epoch in range(start, max_epochs):
            yield epoch
            snap = os.path.join(self.dirname, f"snapshot-{epoch + 1}")
            self.save_fn(snap)
            tmp = self._status_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"epoch": epoch + 1, "snapshot": snap}, f)
                f.flush()
                os.fsync(f.fileno())   # the commit record must be durable
            if _faults._ENABLED:
                # crash point between snapshot and commit: the interrupted
                # epoch replays exactly once on resume
                _faults.check("ckpt.commit")
            os.replace(tmp, self._status_path())  # atomic commit
            prev = os.path.join(self.dirname, f"snapshot-{epoch}")
            if os.path.isdir(prev):
                import shutil
                shutil.rmtree(prev, ignore_errors=True)
            elif os.path.isfile(prev):  # save_fn may write one file per snap
                try:
                    os.remove(prev)
                except OSError:
                    pass
