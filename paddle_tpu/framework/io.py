# placeholder; real paddle.save/load lands with the checkpoint milestone
def save(obj, path, **kw):
    raise NotImplementedError


def load(path, **kw):
    raise NotImplementedError
