"""paddle.save / paddle.load — object checkpointing.

Reference parity: `python/paddle/framework/io.py:562,778` (pickle + per-
tensor payloads; handles Layer state_dict and optimizer state). Tensors are
stored as numpy inside an npz sidecar to keep the pickle small and portable.
"""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor


class _TensorRef:
    def __init__(self, key, is_param, name):
        self.key, self.is_param, self.name = key, is_param, name


def _pack(obj, store, prefix=""):
    if isinstance(obj, Tensor):
        key = f"t{len(store)}"
        store[key] = np.asarray(obj._value)
        return _TensorRef(key, isinstance(obj, Parameter), obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v, store) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_pack(v, store) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    if isinstance(obj, jnp.ndarray):
        key = f"t{len(store)}"
        store[key] = np.asarray(obj)
        return _TensorRef(key, False, None)
    return obj


def _unpack(obj, store, return_numpy=False):
    if isinstance(obj, _TensorRef):
        arr = store[obj.key]
        if return_numpy:
            return arr
        t = Parameter(jnp.asarray(arr), name=obj.name) if obj.is_param else \
            Tensor(jnp.asarray(arr), name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, store, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_unpack(v, store, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    """Crash-atomic: the payload is fully serialized in memory, then
    committed through sharded_io's tmp+fsync+rename path — a SIGKILL
    mid-save (e.g. inside `hapi.ModelCheckpoint` at epoch end) can never
    leave a torn `.pdparams`/`.pdopt` under the committed name."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    store = {}
    packed = _pack(obj, store)
    buf = _io.BytesIO()
    np.savez(buf, **store)
    blob = pickle.dumps({"__paddle_tpu__": 1, "obj": packed,
                         "npz": buf.getvalue()}, protocol=protocol)
    from .sharded_io import atomic_write
    atomic_write(path, blob)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        blob = pickle.load(f)
    if not (isinstance(blob, dict) and "__paddle_tpu__" in blob):
        return blob  # plain pickle fallback
    store = dict(np.load(_io.BytesIO(blob["npz"]), allow_pickle=False))
    return _unpack(blob["obj"], store, return_numpy)
