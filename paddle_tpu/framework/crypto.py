"""Model-file encryption (AES-128-CTR).

Reference parity: `paddle/fluid/framework/io/crypto/` (`CipherUtils`,
`AESCipher` — encrypt saved programs/parameters at rest). The cipher is
native C++ (`csrc/crypto.cpp`, FIPS-197, validated against NIST SP
800-38A vectors); keys are derived from a passphrase with PBKDF2-SHA256
and a random per-file IV is stored in the header.
"""
from __future__ import annotations

import ctypes
import hashlib
import os

_MAGIC = b"PDENC1\0\0"


def _lib():
    from .. import _native
    lib = _native._load()
    if not lib:  # _load() returns False on build failure
        raise RuntimeError("native crypto unavailable (no C++ toolchain)")
    lib.aes128_ctr_crypt.restype = ctypes.c_int
    lib.aes128_ctr_crypt.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                     ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_ubyte),
                                     ctypes.c_uint64]
    return lib


def _derive_key(passphrase: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt,
                               10_000, dklen=16)


def _ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    out = (ctypes.c_ubyte * len(data))()
    rc = _lib().aes128_ctr_crypt(key, iv, data, out, len(data))
    if rc != 0:
        raise RuntimeError("aes128_ctr_crypt failed")
    return bytes(out)


def encrypt_bytes(data: bytes, passphrase: str) -> bytes:
    """header(magic + salt + iv) || AES-128-CTR(data)."""
    salt = os.urandom(16)
    iv = os.urandom(16)
    key = _derive_key(passphrase, salt)
    return _MAGIC + salt + iv + _ctr(key, iv, data)


def decrypt_bytes(blob: bytes, passphrase: str) -> bytes:
    if blob[:8] != _MAGIC:
        raise ValueError("not a paddle_tpu-encrypted blob")
    if len(blob) < 40:  # magic + salt + iv: truncated file
        raise ValueError("encrypted blob truncated (header incomplete)")
    salt, iv = blob[8:24], blob[24:40]
    key = _derive_key(passphrase, salt)
    return _ctr(key, iv, blob[40:])


def encrypt_file(path: str, out_path: str, passphrase: str):
    """CipherUtils::EncryptToFile role (model artifacts at rest)."""
    with open(path, "rb") as f:
        blob = encrypt_bytes(f.read(), passphrase)
    with open(out_path, "wb") as f:
        f.write(blob)


def decrypt_file(path: str, out_path: str, passphrase: str):
    with open(path, "rb") as f:
        data = decrypt_bytes(f.read(), passphrase)
    with open(out_path, "wb") as f:
        f.write(data)
