"""Program version stamping + op version registry.

Reference parity: `framework/version.cc` (program artifacts carry the
framework version that wrote them; loaders check compatibility) and
`framework/op_version_registry.h` (per-op semantic version + checkpoints
describing each behavior change, so converters can upgrade old programs).

TPU-native use: `paddle_tpu.jit.save` artifacts embed
{framework_version, op_versions}; load warns/raises on incompatible
semantic changes instead of silently misreading old modules.
"""
from __future__ import annotations

from typing import Dict, List, Optional

FRAMEWORK_VERSION = "2.0.0-tpu"
# artifacts written by versions < this cannot be read (format breaks)
MIN_COMPATIBLE_VERSION = "2.0.0-tpu"


def _ver_tuple(v: str):
    return tuple(int(x) for x in v.split("-")[0].split("."))


def is_compatible(artifact_version: Optional[str]) -> bool:
    if not artifact_version:
        return False
    try:
        return _ver_tuple(artifact_version) >= _ver_tuple(MIN_COMPATIBLE_VERSION)
    except ValueError:  # malformed/foreign version string -> incompatible
        return False


class OpCheckpoint:
    def __init__(self, note: str, version: int):
        self.note = note
        self.version = version


class OpVersionRegistry:
    """op name -> ordered checkpoints (op_version_registry.h role)."""

    def __init__(self):
        self._ops: Dict[str, List[OpCheckpoint]] = {}

    def register(self, op_name: str):
        self._ops.setdefault(op_name, [])
        return _OpVersionBuilder(self, op_name)

    def _add(self, op_name: str, note: str):
        cps = self._ops.setdefault(op_name, [])
        cps.append(OpCheckpoint(note, len(cps) + 1))

    def version_of(self, op_name: str) -> int:
        return len(self._ops.get(op_name, []))

    def checkpoints(self, op_name: str) -> List[OpCheckpoint]:
        return list(self._ops.get(op_name, []))

    def snapshot(self) -> Dict[str, int]:
        """{op: version} map stamped into saved artifacts."""
        return {k: len(v) for k, v in self._ops.items()}

    def incompatibilities(self, artifact_ops: Dict[str, int]) -> List[str]:
        """Ops whose semantics changed since the artifact was written."""
        out = []
        for op, ver in (artifact_ops or {}).items():
            cur = self.version_of(op)
            if cur > ver:
                notes = "; ".join(c.note for c in self._ops[op][ver:])
                out.append(f"{op}: v{ver} -> v{cur} ({notes})")
        return out


class _OpVersionBuilder:
    def __init__(self, reg: OpVersionRegistry, op_name: str):
        self._reg = reg
        self._op = op_name

    def add_checkpoint(self, note: str):
        self._reg._add(self._op, note)
        return self


GLOBAL_OP_VERSION_REGISTRY = OpVersionRegistry()

# semantic-change history of this framework's own ops (grows over rounds)
GLOBAL_OP_VERSION_REGISTRY.register("sequence_pad").add_checkpoint(
    "maxlen smaller than the longest sequence now raises instead of "
    "silently padding to the true max")
GLOBAL_OP_VERSION_REGISTRY.register("embedding").add_checkpoint(
    "sparse=True emits SelectedRows weight gradients")
