"""paddle.flops — per-layer FLOPs estimation.

Reference parity: `python/paddle/hapi/dynamic_flops.py` (`paddle.flops`:
forward hooks count multiply-adds per supported layer; prints a table and
returns the total).
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor


def _numel(shape):
    return int(np.prod(shape)) if shape else 1


def _count(layer, x, y):
    """FLOPs for one forward call of `layer` (x: first input, y: output)."""
    from ..nn.layer.conv import _ConvNd
    out_e = _numel(y.shape)
    if isinstance(layer, _ConvNd):  # covers Conv*D AND Conv*DTranspose
        # MACs per output element = Cin/groups * prod(K) for both
        # orientations (transpose weights are [Cin, Cout/g, K...])
        kk = _numel(layer.kernel_size)
        kin = (layer.in_channels // layer.groups) * kk
        return 2 * kin * out_e
    if isinstance(layer, nn.Linear):
        return 2 * layer.weight.shape[0] * out_e
    if isinstance(layer, (nn.BatchNorm1D, nn.BatchNorm2D, nn.LayerNorm)):
        return 2 * out_e
    if type(layer).__name__.endswith(("Pool2D", "Pool1D", "Pool3D")):
        return _numel(x.shape)
    if isinstance(layer, (nn.ReLU, nn.GELU, nn.Sigmoid)):
        return out_e
    return 0


def flops(net, input_size, custom_ops=None, print_detail=False) -> int:
    """Count forward FLOPs of `net` on a dummy input of `input_size`
    (paddle.flops parity). custom_ops: {LayerType: fn(layer, x, y) -> int}."""
    counts = []
    hooks = []

    def make_hook(layer):
        def hook(lyr, inputs, output):
            x = inputs[0] if inputs else None
            y = output[0] if isinstance(output, (list, tuple)) else output
            if not isinstance(y, Tensor):
                return
            fn = (custom_ops or {}).get(type(lyr))
            n = fn(lyr, x, y) if fn else _count(lyr, x, y)
            if n:
                counts.append((type(lyr).__name__, n))
        return hook

    seen = set()
    for lyr in net.sublayers(include_self=True):
        # leaves only, ONE hook per object: a weight-shared layer appears
        # once per registration but must count once per forward call
        if not lyr._sub_layers and id(lyr) not in seen:
            seen.add(id(lyr))
            hooks.append(lyr.register_forward_post_hook(make_hook(lyr)))
    was_training = net.training
    net.eval()
    try:
        net(Tensor(np.zeros(input_size, np.float32)))
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    total = sum(n for _, n in counts)
    if print_detail:
        for name, n in counts:
            print(f"{name:<24}{n:>16,}")
        print(f"{'Total FLOPs':<24}{total:>16,}")
    return total
