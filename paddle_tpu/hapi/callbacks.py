"""hapi callbacks. Reference parity: `python/paddle/hapi/callbacks.py`."""
from __future__ import annotations

import time


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done in {dt:.1f}s: {items}")


class ModelCheckpoint(Callback):
    """Epoch-end save. Writes go through `Model.save` ->
    `framework.io.save`, which commits via sharded_io's crash-atomic
    tmp+fsync+rename path — a SIGKILL mid-epoch-end cannot leave a torn
    `.pdparams`/`.pdopt` under the committed name."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"
        self.stopped = False

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        import math
        if not math.isfinite(cur):
            # NaN/Inf metric is a strict regression: NaN comparisons are
            # always False, so without this branch a diverged run would
            # never trip the stop (and a NaN could be stored as `best`,
            # poisoning every later comparison)
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True
                self.model.stop_training = True
            return
        better = (self.best is None or
                  (cur < self.best - self.min_delta if self.mode == "min"
                   else cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


def config_callbacks(callbacks, model, epochs, steps, log_freq=10, verbose=2,
                     save_freq=1, save_dir=None, metrics=None, mode="train"):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs) and verbose:
        cbs.append(ProgBarLogger(log_freq, verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbs):
        cbs.append(LRScheduler())
    for c in cbs:
        c.set_model(model)
        c.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                      "metrics": metrics or []})
    return cbs
