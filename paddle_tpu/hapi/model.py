"""hapi.Model — Keras-like fit/evaluate/predict.

Reference parity: `python/paddle/hapi/model.py:906 (fit), 1556 (evaluate),
1786 (predict), 1889 (save)`. TPU-first: `fit` drives the jitted TrainStep
(one XLA program per step — forward+backward+update), not op-by-op dygraph.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from .callbacks import config_callbacks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics is not None else [])
        amp_dtype = None
        if amp_configs:
            level = amp_configs.get("level", "O1") if isinstance(amp_configs, dict) \
                else amp_configs
            if level in ("O1", "O2"):
                amp_dtype = amp_configs.get("dtype", "bfloat16") if \
                    isinstance(amp_configs, dict) else "bfloat16"
        if optimizer is not None and loss is not None:
            from ..jit.train_step import TrainStep
            self._train_step = TrainStep(self.network, loss, optimizer,
                                         amp_dtype=amp_dtype)
        return self

    def _as_loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        if self._train_step is not None and update:
            self._train_step._n_model_inputs = len(inputs)
            loss = self._train_step(*inputs, *(labels or []))
            return float(loss.numpy())
        out = self.network(*inputs)
        loss = self._loss(out, *(labels or []))
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return float(loss.numpy())

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(labels, (list, tuple)) else [labels]
        out = self.network(*inputs)
        loss = self._loss(out, *(labels or [])) if self._loss else None
        metrics = []
        for m in self._metrics:
            r = m.compute(out, *(labels or []))
            m.update(*r) if isinstance(r, tuple) else m.update(r)
            metrics.append(m.accumulate())
        return (float(loss.numpy()) if loss is not None else None), metrics

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core.autograd import no_grad
        with no_grad():
            out = self.network(*inputs)
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, guard=None,
            prefetch=None):
        """`guard`: a `paddle_tpu.guard.TrainGuard` wrapping this model's
        TrainStep. Every train step then runs supervised (watchdog,
        divergence rollback, desync check, preemption checkpoint), and a
        prior `guard.resume()` fast-forwards the loop to the checkpointed
        epoch/batch cursor. A preemption raises `PreemptedError` out of
        fit AFTER the loop state was committed.

        `prefetch`: feed the train loop through an async device prefetcher
        (io.prefetch.DevicePrefetcher): a feeder thread stages batches on
        device FLAGS_prefetch_depth ahead, hiding h2d + host batch assembly
        under the previous step. None = follow FLAGS_prefetch. Composes
        with `guard`: the cursor counts CONSUMED batches only, so a
        preemption drops at most `depth` staged batches — they are
        re-produced on resume (never double-trained, never skipped)."""
        loader = self._as_loader(train_data, batch_size, shuffle)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbs = config_callbacks(callbacks, self, epochs, steps, log_freq, verbose,
                               save_freq, save_dir,
                               metrics=[m.name() for m in self._metrics])
        if guard is not None and self._train_step is None:
            raise ValueError("fit(guard=...) requires prepare() with an "
                             "optimizer and a loss (the jitted TrainStep is "
                             "what the guard supervises)")
        from ..io import prefetch as _prefetch
        if prefetch is None:
            feed = _prefetch.maybe_wrap(loader, step=self._train_step)
        elif prefetch:
            feed = _prefetch.DevicePrefetcher(loader, step=self._train_step)
        else:
            feed = loader
        cursor = guard.resume_cursor if guard is not None else None
        self.stop_training = False
        for cb in cbs:
            cb.on_train_begin()
        it = 0
        try:
            for epoch in range(epochs):
                if cursor and epoch < cursor[0]:
                    continue  # resumed past this epoch entirely
                for cb in cbs:
                    cb.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                for step, batch in enumerate(feed):
                    if cursor and (epoch, step) < tuple(cursor):
                        continue  # resumed past this batch
                    for cb in cbs:
                        cb.on_train_batch_begin(step)
                    inputs, labels = self._split_batch(batch)
                    if guard is not None:
                        self.network.train()
                        guard.set_cursor(epoch, step)
                        self._train_step._n_model_inputs = len(inputs)
                        loss = guard.step(*inputs, *(labels or []))
                        if loss is None:  # divergence guard skipped the batch
                            continue
                    else:
                        loss = self.train_batch(inputs, labels)
                    logs = {"loss": loss}
                    for cb in cbs:
                        cb.on_train_batch_end(step, logs)
                    it += 1
                    if (num_iters and it >= num_iters) or self.stop_training:
                        break
                cursor = None  # fast-forward applies to the first epoch only
                for cb in cbs:
                    cb.on_epoch_end(epoch, logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                              verbose=0, num_workers=num_workers)
                    for cb in cbs:
                        cb.on_eval_end(eval_logs)
                if (num_iters and it >= num_iters) or self.stop_training:
                    break
        finally:
            # stop the feeder and DROP in-flight prefetched batches — on a
            # preemption they sit beyond the committed cursor and will be
            # re-produced by the resumed run's fast-forwarded loader
            if feed is not loader:
                feed.close()
        for cb in cbs:
            cb.on_train_end(logs)
        return self

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        if isinstance(batch, (list, tuple)):
            return list(batch), None
        return [batch], None

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._as_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            loss, _ = self.eval_batch(inputs, labels)
            if loss is not None:
                losses.append(loss)
        logs = {"loss": float(np.mean(losses)) if losses else None}
        for m in self._metrics:
            name = m.name()
            logs[name if isinstance(name, str) else name[0]] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            inputs = batch if not isinstance(batch, (list, tuple)) else batch[0]
            outputs.append(self.predict_batch(inputs))
        return outputs

    def save(self, path, training=True):
        from ..framework.io import save as fsave
        if training:
            fsave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                fsave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            raise ValueError("inference save requires input_spec: use paddle.jit.save")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary_str(self.network)


def summary_str(network):
    lines = []
    total = 0
    for name, p in network.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        lines.append(f"{name:60s} {str(p.shape):24s} {n:>12,d}")
    lines.append(f"{'Total params:':60s} {'':24s} {total:>12,d}")
    return "\n".join(lines)


def summary(net, input_size=None, dtypes=None):
    s = summary_str(net)
    print(s)
    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    return {"total_params": total, "trainable_params":
            sum(int(np.prod(p.shape)) for p in net.parameters() if not p.stop_gradient)}
