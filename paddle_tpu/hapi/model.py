# placeholder; real hapi.Model lands with the training API milestone
class Model:
    def __init__(self, *a, **kw):
        raise NotImplementedError("hapi.Model arrives after nn/optimizer")


def summary(net, input_size=None, dtypes=None):
    raise NotImplementedError
