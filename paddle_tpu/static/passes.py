"""User-extensible program passes.

Reference parity: the IR pass framework (`paddle/fluid/framework/ir/pass.h`,
`PassRegistry`) — the reference rewrites Program/SSA graphs with named,
registered passes (fuse_*, memory_optimize, ...).

TPU-first redesign: XLA already owns low-level rewriting (fusion, layout,
DCE), so the surviving extension point is the FUNCTION level, where jax is
natively composable. A pass is `Callable[[fn], fn]`; it can be a simple
wrapper (remat, precision casts) or a jaxpr REINTERPRETER that substitutes
chosen primitives (`make_op_rewrite_pass` — the fuse-pass role: swap an op
cluster for a custom kernel). `Program.apply_pass(name)` re-lowers through
the transformed function, so introspection (`ops()`, `op_histogram()`)
sees the rewritten program.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
from jax.extend import core as jex_core

__all__ = ["register_pass", "get_pass", "list_passes", "apply_pass",
           "make_op_rewrite_pass"]

_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str, pass_fn: Callable = None):
    """Register a function-to-function transform under `name`.

    Usable directly or as a decorator::

        @register_pass("my_pass")
        def my_pass(fn):
            def wrapped(*args):
                return fn(*args)
            return wrapped
    """
    if callable(name):
        raise TypeError(
            "register_pass needs a name: use @register_pass(\"my_pass\")")
    if pass_fn is None:
        def deco(f):
            _REGISTRY[name] = f
            return f
        return deco
    _REGISTRY[name] = pass_fn
    return pass_fn


def get_pass(name: str) -> Callable:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown pass {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_passes():
    return sorted(_REGISTRY)


def apply_pass(program, name: str, **options):
    """Return a NEW Program with the named pass applied to its function.

    Two pass kinds: function passes (`Callable[[fn], fn]`, the default) and
    PROGRAM passes (marked `_program_pass = True`) which receive the whole
    Program — analysis passes like 'lint' need the arg specs, not just the
    function."""
    from .program import Program
    p = get_pass(name)
    if getattr(p, "_program_pass", False):
        return p(program, **options)
    new_fn = p(program._fn, **options)
    return Program.from_callable(new_fn, program._arg_specs,
                                 name=f"{program.name}+{name}")


# ---- jaxpr reinterpretation: the op-rewrite (fuse-pass) mechanism ----

def _call_impl(impl, invals, params):
    """Invoke a rewrite impl with only the eqn params its signature takes
    (primitives carry params like `accuracy` that impls rarely care about;
    a **kwargs impl still receives everything)."""
    import inspect
    try:
        sig = inspect.signature(impl)
    except (TypeError, ValueError):
        return impl(*invals)
    if any(p.kind == p.VAR_KEYWORD for p in sig.parameters.values()):
        return impl(*invals, **params)
    keep = {k: v for k, v in params.items() if k in sig.parameters}
    return impl(*invals, **keep)


_warned_regions = set()


def _warn_if_skipped_region(eqn, rewrites):
    """Control-flow bodies (scan/while/cond) are not reinterpreted; warn
    once per primitive when they contain an op the user asked to rewrite,
    instead of silently leaving it in place."""
    import warnings

    def sub_jaxprs(params):
        for v in params.values():
            for c in (v if isinstance(v, (tuple, list)) else (v,)):
                if hasattr(c, "jaxpr"):
                    yield c.jaxpr
                elif hasattr(c, "eqns"):
                    yield c

    for sub in sub_jaxprs(eqn.params):
        for inner in sub.eqns:
            if inner.primitive.name in rewrites:
                key = (eqn.primitive.name, inner.primitive.name)
                if key not in _warned_regions:
                    _warned_regions.add(key)
                    warnings.warn(
                        f"op-rewrite pass: '{inner.primitive.name}' inside "
                        f"a '{eqn.primitive.name}' body is NOT rewritten "
                        "(control-flow regions are executed as-is)")


def _eval_with_rewrites(jaxpr, consts, rewrites, *args):
    env = {}

    def read(v):
        return v.val if isinstance(v, jex_core.Literal) else env[v]

    def write(v, val):
        env[v] = val

    for v, c in zip(jaxpr.constvars, consts):
        write(v, c)
    for v, a in zip(jaxpr.invars, args):
        write(v, a)
    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        prim = eqn.primitive.name
        if prim in rewrites:
            out = _call_impl(rewrites[prim], invals, eqn.params)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
        elif "jaxpr" in eqn.params and prim not in ("scan", "while", "cond"):
            # recurse into single-body regions (pjit/jit, remat/checkpoint,
            # closed_call, ...) so rewrites apply inside them too
            inner = eqn.params["jaxpr"]
            if hasattr(inner, "jaxpr"):        # ClosedJaxpr
                sub, consts_ = inner.jaxpr, inner.consts
            else:                              # plain Jaxpr (remat)
                sub, consts_ = inner, ()
            outs = _eval_with_rewrites(sub, consts_, rewrites, *invals)
        else:
            _warn_if_skipped_region(eqn, rewrites)
            outs = eqn.primitive.bind(*invals, **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
        for v, o in zip(eqn.outvars, outs):
            write(v, o)
    return [read(v) for v in jaxpr.outvars]


def make_op_rewrite_pass(rewrites: Dict[str, Callable]) -> Callable:
    """Build a pass substituting jax primitives by name.

    `rewrites` maps primitive names (see `Program.op_histogram()`) to
    replacement callables invoked as `impl(*invals, **eqn_params)` — the
    reference's fuse-pass role (swap an op for a bespoke kernel)."""

    def pass_fn(fn):
        def rewritten(*args):
            closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
            out = _eval_with_rewrites(closed.jaxpr, closed.consts, rewrites,
                                      *args)
            # restore the original fn's output PYTREE, not just arity
            treedef = jax.tree_util.tree_structure(out_shape)
            return jax.tree_util.tree_unflatten(treedef, out)
        return rewritten

    return pass_fn


# ---- builtin passes (reference pass-library counterparts) ----

@register_pass("remat")
def _remat_pass(fn):
    """Whole-program rematerialization (`memory_optimize_pass` role):
    backward recomputes instead of saving residuals."""
    return jax.checkpoint(fn)


@register_pass("bf16_io")
def _bf16_io_pass(fn):
    """Cast floating inputs to bf16 before the body (fuse_bf16 role)."""
    def wrapped(*args):
        cast = [a.astype(jnp.bfloat16)
                if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                          jnp.floating)
                else a for a in args]
        return fn(*cast)
    return wrapped


def _eval_live(jaxpr, consts, live, *args):
    """Re-execute only the live eqns (liveness guarantees a dead eqn's
    outputs are never read downstream). Recurses into single-body regions
    (pjit/jit, remat, closed_call — same region policy as
    `_eval_with_rewrites`), so a to_static capture's pjit wrapper is DCE'd
    through; scan/while/cond bodies stay atomic."""
    from ..analysis.graph import live_eqn_mask
    env = {}

    def read(v):
        return v.val if isinstance(v, jex_core.Literal) else env[v]

    def write(v, val):
        env[v] = val

    for v, c in zip(jaxpr.constvars, consts):
        write(v, c)
    for v, a in zip(jaxpr.invars, args):
        write(v, a)
    for eqn, keep in zip(jaxpr.eqns, live):
        if not keep:
            continue
        invals = [read(v) for v in eqn.invars]
        prim = eqn.primitive.name
        if "jaxpr" in eqn.params and prim not in ("scan", "while", "cond"):
            inner = eqn.params["jaxpr"]
            if hasattr(inner, "jaxpr"):        # ClosedJaxpr
                sub, consts_ = inner.jaxpr, inner.consts
            else:                              # plain Jaxpr (remat)
                sub, consts_ = inner, ()
            outs = _eval_live(sub, consts_, live_eqn_mask(sub), *invals)
        else:
            outs = eqn.primitive.bind(*invals, **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
        for v, o in zip(eqn.outvars, outs):
            write(v, o)
    return [read(v) for v in jaxpr.outvars]


@register_pass("dead_op_elim")
def _dead_op_elim_pass(fn):
    """Dead-op elimination backed by tpu-lint's liveness analysis
    (`analysis.graph.live_eqn_mask`) — the reference's
    `identity_op_clean`/DCE pass family. XLA would DCE the dead work at
    compile anyway; eliminating it HERE shrinks the traced program, so
    introspection (`ops()`, golden snapshots), lowering, and compile all
    stop paying for ops whose results nothing consumes. Descends through
    single-body regions (pjit/remat); scan/while/cond bodies stay atomic
    (live iff consumed)."""
    def rewritten(*args):
        from ..analysis.graph import live_eqn_mask
        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
        live = live_eqn_mask(closed.jaxpr)
        out = _eval_live(closed.jaxpr, closed.consts, live, *args)
        treedef = jax.tree_util.tree_structure(out_shape)
        return jax.tree_util.tree_unflatten(treedef, out)
    return rewritten


def _lint_pass(program, fail_on: str = None):
    """Analysis-only PROGRAM pass: run tpu-lint's graph rules (dead ops,
    unused inputs, f64 widenings, host callbacks) over the program and its
    source lint over the captured function. Findings are warned and stored
    on the returned program as `.lint_findings`; with `fail_on=` set
    ('warning'/'error'), findings at/above that severity raise ValueError
    — the compile-time gate (`apply_pass(prog, 'lint', fail_on='error')`)."""
    import warnings
    from ..analysis import lint_callable
    from ..analysis.base import severity_at_least
    from ..analysis.graph import analyze_program
    findings = analyze_program(program)
    findings += lint_callable(program._fn)
    for f in findings:
        warnings.warn(f"tpu-lint[pass]: {f.format()}")
    program.lint_findings = findings
    if fail_on is not None:
        bad = [f for f in findings if severity_at_least(f.severity, fail_on)]
        if bad:
            raise ValueError(
                f"lint pass: {len(bad)} finding(s) at/above {fail_on}:\n" +
                "\n".join(f.format() for f in bad))
    return program


_lint_pass._program_pass = True
register_pass("lint", _lint_pass)


def _concurrency_pass(program, fail_on: str = None):
    """Analysis-only PROGRAM pass: run tpu-lint's concurrency rules
    (lock-order, blocking-under-lock, unregistered-thread) over the
    module that defines the captured function — the threading context
    the program executes in, not the jaxpr itself. Findings are warned
    and stored as `.concurrency_findings`; `fail_on=` gates like the
    lint pass. Builtins/C functions have no source file: no findings."""
    import inspect
    import warnings
    from ..analysis.base import severity_at_least
    from ..analysis.concurrency import analyze_paths
    try:
        path = inspect.getsourcefile(program._fn)
    except TypeError:
        path = None
    findings = analyze_paths([path])[0] if path else []
    for f in findings:
        warnings.warn(f"tpu-lint[pass]: {f.format()}")
    program.concurrency_findings = findings
    if fail_on is not None:
        bad = [f for f in findings if severity_at_least(f.severity, fail_on)]
        if bad:
            raise ValueError(
                f"concurrency pass: {len(bad)} finding(s) at/above "
                f"{fail_on}:\n" + "\n".join(f.format() for f in bad))
    return program


_concurrency_pass._program_pass = True
register_pass("concurrency", _concurrency_pass)
