"""Structured control flow for static capture.

Reference parity: `operators/controlflow/` (`conditional_block_op.cc`,
`while_op.cc`) exposed as `paddle.static.nn.cond/while_loop/case/switch_case`.
TPU-native: these ARE `lax.cond`/`lax.while_loop` — the XLA-compilable
control flow that @to_static requires for data-dependent branches.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _unwrap(x):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap(x):
    # Tensors PASS THROUGH: Tensor is pytree-registered, so a bare
    # tree_map(Tensor, x) would rebuild a fresh Tensor around the value
    # and SEVER the autograd tape of any intermediate fed into a builder
    if isinstance(x, Tensor):
        return x
    return jax.tree_util.tree_map(
        lambda v: v if isinstance(v, Tensor) else Tensor(v), x,
        is_leaf=lambda v: isinstance(v, Tensor))


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    p = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
    out = jax.lax.cond(p.reshape(()),
                       lambda _: _unwrap(true_fn()),
                       lambda _: _unwrap(false_fn()),
                       operand=None)
    return _wrap(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    init = _unwrap(list(loop_vars))

    def c(vs):
        r = cond_fn(*_wrap(vs))
        return (r._value if isinstance(r, Tensor) else jnp.asarray(r)).reshape(())

    def b(vs):
        out = body_fn(*_wrap(vs))
        out = out if isinstance(out, (list, tuple)) else [out]
        return _unwrap(list(out))

    final = jax.lax.while_loop(c, b, init)
    return _wrap(final)


def case(pred_fn_pairs, default=None, name=None):
    preds = [p._value.reshape(()) if isinstance(p, Tensor) else jnp.asarray(p)
             for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is not None:
        fns = fns + [default]
    idx = jnp.argmax(jnp.stack([p.astype(jnp.int32) for p in preds] +
                               ([jnp.asarray(1)] if default is not None else [])))
    out = jax.lax.switch(idx, [lambda f=f: _unwrap(f()) for f in fns])
    return _wrap(out)


def switch_case(branch_index, branch_fns, default=None, name=None):
    bi = branch_index._value if isinstance(branch_index, Tensor) else jnp.asarray(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map branch_index -> position
        pos = sum(jnp.where(bi == k, i, 0) for i, k in enumerate(keys))
    else:
        fns = list(branch_fns)
        pos = bi
    if default is not None:
        fns = fns + [default]
        pos = jnp.clip(pos, 0, len(fns) - 1)
    out = jax.lax.switch(pos.reshape(()).astype(jnp.int32),
                         [lambda f=f: _unwrap(f()) for f in fns])
    return _wrap(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Fully-connected builder (static/nn fc): flattens trailing dims from
    num_flatten_dims and applies a scoped Linear; named weight_attr shares
    parameters across calls."""
    import numpy as _np
    from .. import nn
    xt = _wrap(x)
    d = int(_np.prod(xt.shape[num_flatten_dims:]))
    lin = _scoped_layer("fc", _attr_name(weight_attr) or name,
                        lambda: nn.Linear(d, size,
                                          bias_attr=None if bias_attr
                                          is not False else False))
    flat = xt.reshape(list(xt.shape[:num_flatten_dims]) + [d])
    return _maybe_act(lin(flat), activation)


# ---- legacy layer-builder functions (static/nn/common.py role) ------------
# The reference's static.nn.* functions create parameters inside the
# default program's scope at graph-build time. The TPU-era equivalent:
# each call instantiates the corresponding nn.Layer in a module-level
# scope keyed by `param_attr.name` (explicit names SHARE parameters across
# calls — the reference's reuse mechanism), unnamed calls get fresh
# parameters via the unique-name generator, and the computation executes
# immediately (or traces, under to_static/Program capture).

_LAYER_SCOPE: dict = {}


def _scoped_layer(kind, name, factory):
    from ..utils import unique_name as _un
    if name is None:
        key = _un.generate(kind)
        layer = factory()
        _LAYER_SCOPE[key] = layer
        return layer
    key = f"{kind}:{name}"
    layer = _LAYER_SCOPE.get(key)
    if layer is None:
        layer = _LAYER_SCOPE[key] = factory()
    return layer


def _attr_name(attr):
    return getattr(attr, "name", None) if attr is not None else None


def _maybe_act(out, act):
    if not act:
        return out
    import paddle_tpu.nn.functional as F
    return getattr(F, act)(out)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from .. import nn
    x = _wrap(input)
    c = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    bn = _scoped_layer("batch_norm", _attr_name(param_attr) or name,
                       lambda: nn.BatchNorm2D(c, momentum=momentum,
                                              epsilon=epsilon)
                       if x.ndim == 4 else nn.BatchNorm1D(c))
    bn.training = not (is_test or use_global_stats)
    return _maybe_act(bn(x), act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from .. import nn
    emb = _scoped_layer("embedding", _attr_name(param_attr),
                        lambda: nn.Embedding(size[0], size[1],
                                             padding_idx=padding_idx,
                                             sparse=is_sparse))
    return emb(_wrap(input))


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32"):
    """Large-scale PS-backed embedding surface: eager build = sparse-grad
    embedding (SelectedRows grads feed the sparse optimizer/PS tier)."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def _convnd(nd, transpose, input, num_filters, filter_size, stride=1,
            padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None,
            use_cudnn=True, act=None, name=None, output_size=None,
            data_format=None):
    from .. import nn
    x = _wrap(input)
    cin = x.shape[1]
    cls = {(2, False): nn.Conv2D, (2, True): nn.Conv2DTranspose,
           (3, False): nn.Conv3D, (3, True): nn.Conv3DTranspose}[(nd, transpose)]
    kw = dict(stride=stride, padding=padding, dilation=dilation,
              groups=groups or 1)
    conv = _scoped_layer(f"conv{nd}d{'T' if transpose else ''}",
                         _attr_name(param_attr) or name,
                         lambda: cls(cin, num_filters, filter_size,
                                     bias_attr=False if bias_attr is False
                                     else None, **kw))
    return _maybe_act(conv(x), act)


def conv2d(*a, **kw):
    return _convnd(2, False, *a, **kw)


def conv2d_transpose(*a, **kw):
    return _convnd(2, True, *a, **kw)


def conv3d(*a, **kw):
    return _convnd(3, False, *a, **kw)


def conv3d_transpose(*a, **kw):
    return _convnd(3, True, *a, **kw)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    import numpy as _np
    from .. import nn
    x = _wrap(input)
    shape = [int(_np.prod(x.shape[begin_norm_axis:]))]
    ln = _scoped_layer("layer_norm", _attr_name(param_attr) or name,
                       lambda: nn.LayerNorm(shape, epsilon=epsilon))
    flat = x.reshape(list(x.shape[:begin_norm_axis]) + shape)
    return _maybe_act(ln(flat).reshape(list(x.shape)), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import nn
    x = _wrap(input)
    gn = _scoped_layer("group_norm", _attr_name(param_attr) or name,
                       lambda: nn.GroupNorm(groups, x.shape[1],
                                            epsilon=epsilon))
    return _maybe_act(gn(x), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn
    x = _wrap(input)
    inorm = _scoped_layer("instance_norm", _attr_name(param_attr) or name,
                          lambda: nn.InstanceNorm2D(x.shape[1],
                                                    epsilon=epsilon))
    return inorm(x)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """CTR data normalization (static/nn data_norm): normalize features by
    accumulated batch statistics WITHOUT learned affine (unless enabled)."""
    from ..ops._dispatch import run_op
    import jax.numpy as jnp
    x = _wrap(input)

    def f(a):
        mu = jnp.mean(a, axis=0, keepdims=True)
        var = jnp.var(a, axis=0, keepdims=True)
        return (a - mu) / jnp.sqrt(var + epsilon)

    return _maybe_act(run_op(f, [x], "data_norm"), act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn
    xt = _wrap(x)
    n = 1 if mode == "all" else (xt.shape[1] if mode == "channel"
                                 else int(xt.shape[-1]))
    pr = _scoped_layer("prelu", _attr_name(param_attr) or name,
                       lambda: nn.PReLU(num_parameters=n))
    return pr(xt)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn.utils import spectral_normalize, _spectral_mat
    import numpy as _np
    w = _wrap(weight)
    h = int(_np.asarray(_spectral_mat(_np.asarray(w._value), dim)).shape[0])
    u0 = _np.random.RandomState(0).randn(h).astype("float32")
    out, _, _ = spectral_normalize(w, u0 / _np.linalg.norm(u0), dim=dim,
                                   n_power_iterations=power_iters, eps=eps)
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from .. import nn
    xt, yt = _wrap(x), _wrap(y)
    bl = _scoped_layer("bilinear", _attr_name(param_attr) or name,
                       lambda: nn.Bilinear(xt.shape[-1], yt.shape[-1], size))
    return _maybe_act(bl(xt, yt), act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (static/nn row_conv / row_conv_op):
    out[t] = sum_{i=0..k} w[i] * x[t+i] per feature channel."""
    from ..core.tensor import Parameter
    from ..ops._dispatch import run_op
    import jax.numpy as jnp
    import numpy as _np
    x = _wrap(input)                      # [B, T, D]
    k = future_context_size + 1
    key = f"row_conv:{_attr_name(param_attr) or id(x.shape[-1])}:{k}"
    w = _LAYER_SCOPE.get(key)
    if w is None:
        w = _LAYER_SCOPE[key] = Parameter(
            jnp.asarray(_np.random.RandomState(0)
                        .uniform(-0.1, 0.1, (k, int(x.shape[-1])))
                        .astype("float32")))

    def f(a, wt):
        pads = [(0, 0), (0, k - 1), (0, 0)]
        ap = jnp.pad(a, pads)
        out = jnp.zeros_like(a)
        for i in range(k):
            out = out + ap[:, i:i + a.shape[1]] * wt[i][None, None, :]
        return out

    return _maybe_act(run_op(f, [x, w], "row_conv"), act)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (static/nn nce / nce_op): one
    positive + uniformly sampled negatives per example, logistic loss."""
    from ..core.tensor import Parameter
    from ..ops._dispatch import run_op
    import jax
    import jax.numpy as jnp
    import numpy as _np
    x = _wrap(input)                      # [B, D]
    y = _wrap(label)
    d = int(x.shape[-1])
    n_neg = int(num_neg_samples or 10)
    key = f"nce:{_attr_name(param_attr) or d}:{num_total_classes}"
    w = _LAYER_SCOPE.get(key)
    if w is None:
        rngw = _np.random.RandomState(seed)
        w = _LAYER_SCOPE[key] = Parameter(jnp.asarray(
            (rngw.randn(num_total_classes, d) / _np.sqrt(d))
            .astype("float32")))
    # negatives advance with the framework generator each call — a fixed
    # RandomState would replay the SAME noise set every step, collapsing
    # NCE into a static n_neg-way discrimination
    from ..core import random as _rnd
    negs = jax.random.randint(_rnd.next_key(), (int(x.shape[0]), n_neg),
                              0, num_total_classes)
    ids = y._value.astype("int32").reshape(-1)

    def f(a, wt):
        pos_w = jnp.take(wt, ids, axis=0)                # [B, D]
        pos_logit = jnp.sum(a * pos_w, -1)
        neg_w = jnp.take(wt, negs, axis=0)               # [B, K, D]
        neg_logit = jnp.einsum("bd,bkd->bk", a, neg_w)
        loss = jax.nn.softplus(-pos_logit) \
            + jax.nn.softplus(neg_logit).sum(-1)
        return loss[:, None]

    return run_op(f, [x, w], "nce")


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """CRF decode (static/nn crf_decoding over crf_decoding_op): Viterbi
    path under linear-chain CRF transitions. The transition parameter is
    [N+2, N] (rows 0/1 = start/stop transitions, rest the N x N matrix —
    the linear_chain_crf layout). Returns the [B, T] best path; with
    `label` given, returns the per-position correctness mask instead
    (the reference's evaluation mode)."""
    import jax
    import jax.numpy as jnp
    import numpy as _np
    from ..core.tensor import Parameter
    from ..ops._dispatch import nondiff_op
    x = _wrap(input)                           # [B, T, N]
    N = int(x.shape[-1])
    if transition is not None:
        trans = _wrap(transition)
    else:
        key = f"crf_trans:{_attr_name(param_attr) or N}"
        trans = _LAYER_SCOPE.get(key)
        if trans is None:
            trans = _LAYER_SCOPE[key] = Parameter(jnp.asarray(
                (_np.random.RandomState(0).randn(N + 2, N) * 0.1)
                .astype("float32")))
    lens = (_wrap(length)._value.astype("int32") if length is not None
            else jnp.full((x.shape[0],), x.shape[1], jnp.int32))

    def f(p, t):
        start, stop, tr = t[0], t[1], t[2:]
        B, T, _ = p.shape

        def step(carry, xs):
            alpha, tpos = carry
            emit = xs
            sc = alpha[:, :, None] + tr[None]
            bp = jnp.argmax(sc, axis=1)
            new = jnp.max(sc, axis=1) + emit
            live = (tpos < lens)[:, None]
            alpha = jnp.where(live, new, alpha)
            return (alpha, tpos + 1), bp

        alpha0 = start[None] + p[:, 0]
        (alpha, _), bps = jax.lax.scan(
            step, (alpha0, jnp.ones((B,), jnp.int32)),
            jnp.swapaxes(p[:, 1:], 0, 1))
        alpha = alpha + stop[None]
        last = jnp.argmax(alpha, -1).astype(jnp.int32)

        def back(tag, xs):
            bp, tpos = xs
            prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
            live = tpos < lens
            tag = jnp.where(live, prev.astype(jnp.int32), tag)
            return tag, tag

        ts = jnp.arange(1, T, dtype=jnp.int32)
        _, path_rev = jax.lax.scan(back, last, (bps[::-1], ts[::-1]))
        path = jnp.concatenate([path_rev[::-1], last[None]], 0)
        return jnp.swapaxes(path, 0, 1).astype(jnp.int64)

    path = nondiff_op(lambda a, b: f(a, b), [x, trans])
    if label is not None:
        lab = _wrap(label)
        from ..ops._dispatch import nondiff_op as _nd
        return _nd(lambda a, b: (a == b).astype(jnp.int64),
                   [path, lab])
    return path


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (static/nn py_func / py_func_op): runs `func` on the
    numpy values. Eager build: immediate host call; under jit capture the
    call routes through jax.pure_callback with the declared `out` spec."""
    import numpy as _np
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    arrs = [getattr(a, "_value", a) for a in (_wrap(a) for a in xs)]
    outs_spec = out if isinstance(out, (list, tuple)) else [out]
    if any(isinstance(a, jax.core.Tracer) for a in arrs):
        specs = [jax.ShapeDtypeStruct(tuple(o.shape), _np.dtype(o.dtype))
                 for o in outs_spec]

        def host(*np_args):
            r = func(*np_args)
            r = r if isinstance(r, (list, tuple)) else [r]
            return [_np.asarray(v) for v in r]

        res = jax.pure_callback(host, specs, *arrs)
        res = res if isinstance(res, (list, tuple)) else [res]
        outs = [Tensor(v) for v in res]
    else:
        r = func(*[_np.asarray(a) for a in arrs])
        r = r if isinstance(r, (list, tuple)) else [r]
        outs = [Tensor(jnp.asarray(_np.asarray(v))) for v in r]
    return outs[0] if not isinstance(out, (list, tuple)) else outs


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD detection head (static/nn multi_box_head): per feature map a
    conv predicts box offsets + class scores against generated priors."""
    import itertools as _it
    import numpy as _np
    import paddle_tpu as paddle
    from .. import nn
    locs, confs, boxes, vars_ = [], [], [], []
    n_in = len(inputs)
    if min_sizes is None:
        min_ratio, max_ratio = min_ratio or 20, max_ratio or 90
        step = int((max_ratio - min_ratio) / max(n_in - 2, 1))
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n_in - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n_in - 1]
    for i, feat in enumerate(inputs):
        f = _wrap(feat)
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        # priors per cell must equal the sizes generated below EXACTLY:
        # min box + (sqrt(min*max) box when max_sizes) + per non-1 aspect
        # ratio one box (two when flipped)
        n_ar = len([a for a in ar if a != 1])
        n_prior = 1 + (1 if max_sizes else 0) + n_ar * (2 if flip else 1)
        loc_conv = _scoped_layer(f"mbox_loc{i}", None,
                                 lambda f=f, n=n_prior: nn.Conv2D(
                                     f.shape[1], n * 4, kernel_size,
                                     padding=pad, stride=stride))
        conf_conv = _scoped_layer(f"mbox_conf{i}", None,
                                  lambda f=f, n=n_prior: nn.Conv2D(
                                      f.shape[1], n * num_classes,
                                      kernel_size, padding=pad,
                                      stride=stride))
        loc = loc_conv(f)
        conf = conf_conv(f)
        b = loc.shape[0]
        locs.append(loc.transpose([0, 2, 3, 1]).reshape([b, -1, 4]))
        confs.append(conf.transpose([0, 2, 3, 1]).reshape(
            [b, -1, num_classes]))
        # prior boxes for this map
        fh, fw = int(f.shape[2]), int(f.shape[3])
        ih, iw = int(_wrap(image).shape[2]), int(_wrap(image).shape[3])
        sw = steps[i] if steps else iw / fw
        sh = steps[i] if steps else ih / fh
        pri = []
        for yy, xx in _it.product(range(fh), range(fw)):
            cx, cy = (xx + offset) * sw, (yy + offset) * sh
            sizes = [(min_sizes[i], min_sizes[i])]
            if max_sizes:
                s = _np.sqrt(min_sizes[i] * max_sizes[i])
                sizes.append((s, s))
            for a in ar:
                if a == 1:
                    continue
                sizes.append((min_sizes[i] * _np.sqrt(a),
                              min_sizes[i] / _np.sqrt(a)))
                if flip:
                    sizes.append((min_sizes[i] / _np.sqrt(a),
                                  min_sizes[i] * _np.sqrt(a)))
            for bw, bh in sizes:
                box = [(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                       (cx + bw / 2) / iw, (cy + bh / 2) / ih]
                if clip:
                    box = [min(max(v, 0.0), 1.0) for v in box]
                pri.append(box)
        boxes.append(_np.asarray(pri, "float32"))
        vars_.append(_np.tile(_np.asarray(variance, "float32"),
                              (len(pri), 1)))
    mbox_locs = paddle.concat(locs, axis=1)
    mbox_confs = paddle.concat(confs, axis=1)
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    box = Tensor(jnp.asarray(_np.concatenate(boxes, 0)))
    var = Tensor(jnp.asarray(_np.concatenate(vars_, 0)))
    return mbox_locs, mbox_confs, box, var


# sequence_* re-exports over the LoD machinery (ops/sequence.py): the
# static.nn legacy names bind to the padded-dense + lengths forms
from ..ops.sequence import (  # noqa: E402,F401
    sequence_pad, sequence_unpad, sequence_pool, sequence_expand,
    sequence_softmax,
)


def sequence_first_step(input, lengths=None):
    x = _wrap(input)
    return x[:, 0]


def sequence_last_step(input, lengths=None):
    import jax.numpy as jnp
    from ..ops._dispatch import run_op
    x = _wrap(input)
    if lengths is None:
        return x[:, -1]
    idx = _wrap(lengths)._value.astype("int32") - 1

    def f(a):
        return jnp.take_along_axis(
            a, idx[:, None, None].astype("int32"), axis=1)[:, 0]

    return run_op(f, [x], "sequence_last_step")


def sequence_concat(inputs, name=None):
    import paddle_tpu as paddle
    return paddle.concat([_wrap(i) for i in inputs], axis=1)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Sequence convolution over time (static/nn sequence_conv): a 1-D
    conv across the padded-dense time axis."""
    from .. import nn
    x = _wrap(input)                      # [B, T, D]
    conv = _scoped_layer("sequence_conv", _attr_name(param_attr) or name,
                         lambda: nn.Conv1D(x.shape[-1], num_filters,
                                           filter_size,
                                           padding=(filter_size - 1) // 2
                                           if padding else 0))
    out = conv(x.transpose([0, 2, 1])).transpose([0, 2, 1])
    return _maybe_act(out, act)


def sequence_slice(input, offset, length, name=None):
    import jax.numpy as jnp
    from ..ops._dispatch import run_op
    x = _wrap(input)
    off = _wrap(offset)._value.astype("int32").reshape(-1)
    ln = _wrap(length)._value.astype("int32").reshape(-1)
    L = int(ln.max())

    def f(a):
        idx = off[:, None] + jnp.arange(L)[None, :]
        idx = jnp.minimum(idx, a.shape[1] - 1)
        out = jnp.take_along_axis(
            a, idx[..., None] if a.ndim == 3 else idx, axis=1)
        mask = jnp.arange(L)[None, :] < ln[:, None]
        return out * mask[..., None] if a.ndim == 3 else out * mask

    return run_op(f, [x], "sequence_slice")


def sequence_expand_as(x, y, name=None):
    from ..ops.sequence import sequence_expand as _se
    return _se(_wrap(x), _wrap(y))


def sequence_reshape(input, new_dim):
    x = _wrap(input)
    b, t, d = x.shape
    return x.reshape([b, (t * d) // new_dim, new_dim])


def sequence_scatter(input, index, updates, name=None):
    import jax.numpy as jnp
    from ..ops._dispatch import run_op
    x, idx, upd = _wrap(input), _wrap(index), _wrap(updates)
    iv = idx._value.astype("int32")

    def f(a, u):
        rows = jnp.arange(a.shape[0])[:, None]
        return a.at[rows, iv].add(u)

    return run_op(f, [x, upd], "sequence_scatter")


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    import jax.numpy as jnp
    from ..ops._dispatch import run_op
    x = _wrap(input)

    def f(a):
        T = a.shape[1]
        cols = []
        for w in range(win_size):
            sl = a[:, w:]
            padn = T - sl.shape[1]
            cols.append(jnp.pad(sl, [(0, 0), (0, padn)],
                                constant_values=pad_value))
        return jnp.stack(cols, axis=-1)

    return run_op(f, [x], "sequence_enumerate")


def sequence_reverse(x, name=None):
    xt = _wrap(x)
    return xt[:, ::-1]


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None, name=None):
    """static/nn deform_conv2d: builder over vision.ops.deform_conv2d with
    a scope-created weight."""
    from ..core.tensor import Parameter
    import jax.numpy as jnp
    import numpy as _np
    from ..vision.ops import deform_conv2d as _dc
    xt = _wrap(x)
    kh = filter_size if isinstance(filter_size, int) else filter_size[0]
    kw = filter_size if isinstance(filter_size, int) else filter_size[1]
    key = f"deform_conv2d:{_attr_name(weight_attr) or id(num_filters)}"
    w = _LAYER_SCOPE.get(key)
    if w is None:
        cin = int(xt.shape[1]) // groups
        k = 1.0 / _np.sqrt(cin * kh * kw)
        w = _LAYER_SCOPE[key] = Parameter(jnp.asarray(
            _np.random.RandomState(0).uniform(
                -k, k, (num_filters, cin, kh, kw)).astype("float32")))
    return _dc(xt, _wrap(offset), w, None, stride, padding, dilation,
               deformable_groups, groups, mask)
