"""Structured control flow for static capture.

Reference parity: `operators/controlflow/` (`conditional_block_op.cc`,
`while_op.cc`) exposed as `paddle.static.nn.cond/while_loop/case/switch_case`.
TPU-native: these ARE `lax.cond`/`lax.while_loop` — the XLA-compilable
control flow that @to_static requires for data-dependent branches.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _unwrap(x):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap(x):
    return jax.tree_util.tree_map(Tensor, x)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    p = pred._value if isinstance(pred, Tensor) else jnp.asarray(pred)
    out = jax.lax.cond(p.reshape(()),
                       lambda _: _unwrap(true_fn()),
                       lambda _: _unwrap(false_fn()),
                       operand=None)
    return _wrap(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    init = _unwrap(list(loop_vars))

    def c(vs):
        r = cond_fn(*_wrap(vs))
        return (r._value if isinstance(r, Tensor) else jnp.asarray(r)).reshape(())

    def b(vs):
        out = body_fn(*_wrap(vs))
        out = out if isinstance(out, (list, tuple)) else [out]
        return _unwrap(list(out))

    final = jax.lax.while_loop(c, b, init)
    return _wrap(final)


def case(pred_fn_pairs, default=None, name=None):
    preds = [p._value.reshape(()) if isinstance(p, Tensor) else jnp.asarray(p)
             for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is not None:
        fns = fns + [default]
    idx = jnp.argmax(jnp.stack([p.astype(jnp.int32) for p in preds] +
                               ([jnp.asarray(1)] if default is not None else [])))
    out = jax.lax.switch(idx, [lambda f=f: _unwrap(f()) for f in fns])
    return _wrap(out)


def switch_case(branch_index, branch_fns, default=None, name=None):
    bi = branch_index._value if isinstance(branch_index, Tensor) else jnp.asarray(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map branch_index -> position
        pos = sum(jnp.where(bi == k, i, 0) for i, k in enumerate(keys))
    else:
        fns = list(branch_fns)
        pos = bi
    if default is not None:
        fns = fns + [default]
        pos = jnp.clip(pos, 0, len(fns) - 1)
    out = jax.lax.switch(pos.reshape(()).astype(jnp.int32),
                         [lambda f=f: _unwrap(f()) for f in fns])
    return _wrap(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    raise NotImplementedError("static.nn.fc: use paddle_tpu.nn.Linear")
