"""Program: an inspectable, prunable static-program artifact.

Reference parity: ProgramDesc (`paddle/fluid/framework/framework.proto:234` —
Program ⊃ Blocks ⊃ Ops/Vars) with python mirrors (`fluid/framework.py:4624`),
backward-slice pruning for inference export (`framework/prune.cc:1`), and the
"assert on the rewritten program" test technique (SURVEY §4).

TPU-native redesign: the program IS the StableHLO module jax produces for a
traced function. `Program` wraps that module text + the function/specs that
produced it, exposing:
  - ops()/op_histogram(): parsed op list — golden-HLO snapshot tests replace
    the reference's ProgramDesc assertions;
  - inputs()/outputs(): the signature;
  - prune(fetch_ids): re-lower keeping a subset of outputs — XLA dead-code
    elimination performs the backward slice that prune.cc computes by hand;
  - compile()/run: executable artifact (Executor integration).
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence

import jax

__all__ = ["Program", "OpDesc"]

_OP_RE = re.compile(r"=\s+\"?([a-zA-Z_][\w.]*)\"?[ (<]")


class OpDesc:
    """One operation in the program body (ProgramDesc OpDesc mirror)."""

    __slots__ = ("type", "result", "text")

    def __init__(self, type_, result, text):
        self.type = type_          # e.g. "stablehlo.dot_general"
        self.result = result       # e.g. "%3"
        self.text = text           # full line

    def __repr__(self):
        return f"OpDesc({self.type})"


class _VarDesc:
    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name, shape, dtype):
        self.name, self.shape, self.dtype = name, list(shape), dtype

    def __repr__(self):
        return f"Var({self.name}: {self.dtype}{self.shape})"


class Program:
    """An XLA static program captured from a traced function.

    Build via `Program.from_callable(fn, specs)` (specs are
    jax.ShapeDtypeStruct / arrays) or get one from `@to_static` functions /
    `static.default_main_program()`.
    """

    def __init__(self, fn: Callable, arg_specs: Sequence, lowered=None,
                 name: str = "main"):
        self._fn = fn
        self._arg_specs = list(arg_specs)
        self._lowered = lowered
        self._compiled = None
        self.name = name

    # ---- construction ----
    @classmethod
    def from_callable(cls, fn, arg_specs, name="main"):
        specs = [a if isinstance(a, jax.ShapeDtypeStruct)
                 else jax.ShapeDtypeStruct(getattr(a, "shape", ()),
                                           getattr(a, "dtype", None))
                 for a in arg_specs]
        return cls(fn, specs, name=name)

    def _lower(self):
        if self._lowered is None:
            self._lowered = jax.jit(self._fn).lower(*self._arg_specs)
        return self._lowered

    # ---- introspection (ProgramDesc surface) ----
    def as_text(self) -> str:
        """StableHLO module text — the serialized program body."""
        return self._lower().as_text()

    __str__ = as_text

    def ops(self) -> List[OpDesc]:
        out = []
        for line in self.as_text().splitlines():
            line = line.strip()
            m = _OP_RE.search(line)
            if m and "=" in line and line.startswith("%"):
                result = line.split("=", 1)[0].strip()
                out.append(OpDesc(m.group(1), result, line))
        return out

    def op_histogram(self) -> dict:
        """Op-type -> count. The golden-HLO snapshot for program tests."""
        hist: dict = {}
        for op in self.ops():
            hist[op.type] = hist.get(op.type, 0) + 1
        return hist

    def has_op(self, op_type: str) -> bool:
        return any(op.type == op_type or op.type.endswith("." + op_type)
                   for op in self.ops())

    def inputs(self) -> List[_VarDesc]:
        tree = jax.tree_util.tree_leaves(self._arg_specs)
        return [_VarDesc(f"input_{i}", s.shape, str(s.dtype))
                for i, s in enumerate(tree)]

    def outputs(self) -> List[_VarDesc]:
        out_info = jax.eval_shape(self._fn, *self._arg_specs)
        leaves = jax.tree_util.tree_leaves(out_info)
        return [_VarDesc(f"output_{i}", s.shape, str(s.dtype))
                for i, s in enumerate(leaves)]

    def num_blocks(self) -> int:
        # func-level regions in the module (main + called/control-flow fns)
        return self.as_text().count("func.func")

    # ---- prune (framework/prune.cc role) ----
    def prune(self, fetch_ids) -> "Program":
        """Keep only the outputs in `fetch_ids` (indices into the flattened
        output list). The backward slice to just-those-outputs happens in
        XLA's DCE when the narrowed program is re-lowered — the compiler
        computes what prune.cc walks by hand."""
        if isinstance(fetch_ids, int):
            fetch_ids = [fetch_ids]
        ids = list(fetch_ids)
        fn = self._fn

        def pruned(*args):
            out = fn(*args)
            leaves = jax.tree_util.tree_leaves(
                out, is_leaf=lambda x: hasattr(x, "shape"))
            picked = [leaves[i] for i in ids]
            return picked[0] if len(picked) == 1 else tuple(picked)

        return Program(pruned, self._arg_specs, name=f"{self.name}_pruned")

    # ---- pass hook (framework/ir PassRegistry role) ----
    def apply_pass(self, name: str, **options) -> "Program":
        """Apply a registered program pass; returns a NEW Program
        (`static.passes.register_pass` is the extension point)."""
        from .passes import apply_pass
        return apply_pass(self, name, **options)

    # ---- execution ----
    def compile(self):
        if self._compiled is None:
            self._compiled = self._lower().compile()
        return self._compiled

    def run(self, *args):
        return self.compile()(*args)

    def clone(self, for_test=False) -> "Program":
        return Program(self._fn, self._arg_specs, name=self.name)

    def __repr__(self):
        # never triggers lowering (repr must stay cheap for debuggers/logs);
        # op count appears only once the module was already lowered
        ops = f", ops={len(self.ops())}" if self._lowered is not None else ""
        return (f"Program(name={self.name!r}, "
                f"inputs={len(self.inputs())}{ops})")


# module-level "default program" registry (fluid.default_main_program role)
_DEFAULT: List[Optional[Program]] = [None]


def _set_default_program(prog: Program):
    _DEFAULT[0] = prog


def default_main_program() -> Program:
    if _DEFAULT[0] is None:
        raise RuntimeError(
            "no program captured yet: call an @to_static function (or build "
            "one with Program.from_callable) first")
    return _DEFAULT[0]
