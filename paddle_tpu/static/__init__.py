"""paddle.static parity.

On TPU the "static graph" is a jax-traced XLA program (paddle_tpu.jit).
This module keeps the static-mode API surface: InputSpec, control flow
(static.nn.cond/while_loop), inference-model save/load, and a thin Executor
that runs @to_static functions — enough for reference static-style scripts
to port mechanically.
"""
from __future__ import annotations

_STATIC_MODE = [False]

from ..jit.input_spec import InputSpec  # noqa: E402,F401
from . import nn  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    """Maps to jit.save on the captured layer (program == exported StableHLO).
    Pass layer= and input_spec= to use this entry point directly."""
    layer = kwargs.get("layer")
    if layer is not None:
        from ..jit.save_load import save as jsave
        return jsave(layer, path_prefix, input_spec=kwargs.get("input_spec", feed_vars))
    raise NotImplementedError(
        "static save: call paddle_tpu.jit.save(layer, path, input_spec) — the "
        "TPU build captures programs from Layers, not global default programs")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit.save_load import load as jload
    tl = jload(path_prefix)
    feed_names = [f"input_{i}" for i in range(len(tl._meta["input_specs"]))]
    fetch_names = ["output_0"]
    return tl, feed_names, fetch_names


class Executor:
    """Shim: runs TranslatedLayers / @to_static functions (no ProgramDesc)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            args = list(feed.values()) if isinstance(feed, dict) else (feed or [])
            out = program(*args)
            return [o.numpy() for o in (out if isinstance(out, (list, tuple)) else [out])]
        raise NotImplementedError("Executor.run expects a callable program on TPU")


def default_main_program():
    raise NotImplementedError("no global default program on the TPU build; use @to_static")


def default_startup_program():
    raise NotImplementedError("no startup program on the TPU build (functional init)")


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)
