"""paddle.static parity.

On TPU the "static graph" is a jax-traced XLA program (paddle_tpu.jit).
This module keeps the static-mode API surface: InputSpec, control flow
(static.nn.cond/while_loop), inference-model save/load, and a thin Executor
that runs @to_static functions — enough for reference static-style scripts
to port mechanically.
"""
from __future__ import annotations

_STATIC_MODE = [False]

from ..jit.input_spec import InputSpec  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from .program import Program, default_main_program  # noqa: E402,F401
from . import passes  # noqa: E402,F401
from .passes import apply_pass, list_passes, register_pass  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    """Maps to jit.save on the captured layer (program == exported StableHLO).
    Pass layer= and input_spec= to use this entry point directly."""
    layer = kwargs.get("layer")
    if layer is not None:
        from ..jit.save_load import save as jsave
        return jsave(layer, path_prefix, input_spec=kwargs.get("input_spec", feed_vars))
    raise NotImplementedError(
        "static save: call paddle_tpu.jit.save(layer, path, input_spec) — the "
        "TPU build captures programs from Layers, not global default programs")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit.save_load import load as jload
    tl = jload(path_prefix)
    feed_names = [f"input_{i}" for i in range(len(tl._meta["input_specs"]))]
    fetch_names = ["output_0"]
    return tl, feed_names, fetch_names


class Executor:
    """Runs Programs / TranslatedLayers / @to_static functions.

    Reference Executor.run (`fluid/executor.py:611,1095`); here "run a
    program" means executing the compiled XLA artifact."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        import numpy as _np
        args = list(feed.values()) if isinstance(feed, dict) else (feed or [])
        if isinstance(program, Program):
            out = program.run(*[getattr(a, "_value", a) for a in args])
        elif callable(program):
            out = program(*args)
        else:
            raise NotImplementedError(
                "Executor.run expects a Program or callable on TPU")
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [_np.asarray(getattr(o, "_value", o)) for o in outs]

    def train_from_dataset(self, program, dataset, fetch_list=None,
                           print_period=100, debug=False):
        """Dataset-driven training loop (reference `executor.py:1731`
        `_run_from_dataset` -> C++ Trainer/DeviceWorker TrainFiles hot
        loop, SURVEY §3.5). `program` is a callable taking the batch dict
        {slot: array} and returning the loss; the loop host-side feeds
        batches exactly like MultiTrainer+HogwildWorker."""
        import numpy as _np
        losses = []
        for i, batch in enumerate(dataset):
            loss = program(batch)
            losses.append(float(getattr(loss, "_value", loss)))
            if debug and print_period and (i + 1) % print_period == 0:
                print(f"[train_from_dataset] batch {i + 1} "
                      f"loss {losses[-1]:.6f}")
        return losses


def default_startup_program():
    """Functional init: parameters are initialized at construction, so the
    startup program is empty — returned as an empty Program for parity."""
    return Program(lambda: (), [], name="startup")


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


# ---- legacy fluid-static compat surface -----------------------------------
import contextlib as _ctx


class Scope:
    """Variable scope (fluid Scope role): name -> value store backing the
    legacy static API's parameter sharing (static.nn's layer scope)."""

    def __init__(self):
        from .nn import _LAYER_SCOPE
        self._store = _LAYER_SCOPE

    def var(self, name):
        return self._store.get(name)

    def find_var(self, name):
        return self._store.get(name)


_GLOBAL_SCOPE = Scope()


def global_scope():
    return _GLOBAL_SCOPE


@_ctx.contextmanager
def scope_guard(scope):
    """Compat: the eager build has ONE live scope; the guard validates and
    yields (programs execute immediately, so there is no deferred state to
    swap)."""
    yield scope


@_ctx.contextmanager
def program_guard(main_program, startup_program=None):
    """Compat context: ops written inside run eagerly; the main program
    object collects nothing extra (Program capture happens via to_static),
    but the guard keeps legacy call sites running unchanged."""
    yield


@_ctx.contextmanager
def name_scope(prefix=None):
    from ..utils import unique_name as _un
    with _un.guard(prefix or "name_scope"):
        yield


@_ctx.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    """IPU pipeline-shard annotation (compat no-op: no IPU backend; mesh
    sharding is the paddle_tpu.parallel surface)."""
    yield


class IpuStrategy:
    """Accepted-for-compat IPU config carrier (no IPU backend)."""

    def __init__(self):
        self._opts = {}

    def set_graph_config(self, **kw):
        self._opts.update(kw)

    def set_pipelining_config(self, **kw):
        self._opts.update(kw)

    def set_precision_config(self, **kw):
        self._opts.update(kw)


class IpuCompiledProgram:
    def __init__(self, program=None, scope=None, ipu_strategy=None):
        self._program = program

    def compile(self, feed_list=None, fetch_list=None):
        return self._program


class BuildStrategy:
    """Graph-build knobs (fluid BuildStrategy): carried for compat; the
    XLA pipeline owns fusion/memory decisions these used to toggle."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = None


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """fluid CompiledProgram compat: wraps a Program/callable; with_data_
    parallel maps to the mesh data-parallel path at run time."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        return self

    def __call__(self, *args, **kw):
        return self._program(*args, **kw)


class ParallelExecutor(CompiledProgram):
    """fluid ParallelExecutor compat (superseded by CompiledProgram in the
    reference too)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        super().__init__(main_program, build_strategy)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """fluid append_backward: in the eager-tape world this IS
    loss.backward(); returns (param, grad) pairs like the reference —
    for ALL trainable leaves reachable from the loss when
    parameter_list is omitted (the reference's default)."""
    params = parameter_list
    if params is None:
        # walk the tape BEFORE backward frees it: trainable leaf inputs
        from ..core.autograd import _collect
        leaves, seen = [], set()
        if loss._node is not None:
            for node in _collect([loss._node]):
                for t in node.inputs:
                    if (not t.stop_gradient and t._node is None
                            and id(t) not in seen):
                        seen.add(id(t))
                        leaves.append(t)
        params = leaves
    loss.backward()
    out = []
    for p in params:
        if isinstance(p, str):
            continue
        out.append((p, p.grad))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid gradients -> autograd.grad over the tape."""
    from .. import autograd as _ag
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _ag.grad(targets, inputs, grad_outputs=target_gradients,
                    allow_unused=True)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (fluid Print): host-prints the value, passes it
    through unchanged (identity in the compute graph)."""
    import numpy as _np
    v = getattr(input, "_value", input)
    head = message or "Print"
    arr = _np.asarray(v) if not hasattr(v, "aval") else v
    print(f"[{head}] shape={getattr(arr, 'shape', '?')} "
          f"dtype={getattr(arr, 'dtype', '?')}\n{arr if summarize else ''}")
    return input


from .nn import py_func  # noqa: E402,F401


class WeightNormParamAttr:
    """fluid WeightNormParamAttr compat: carries the dim; consumers apply
    nn.utils.weight_norm to the built layer."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """EMA of trainable parameters (fluid ExponentialMovingAverage):
    update() folds current weights in; apply()/restore() swap the shadow
    weights for evaluation — the decay follows the reference's
    min(decay, (1+t)/(10+t)) thresholding."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._step = 0
        self._shadow = {}
        self._backup = {}
        self._params = []

    def _tracked(self, params=None):
        if params is not None:
            self._params = list(params)
        if not self._params:
            raise ValueError("EMA.update: pass params on first call")
        return self._params

    def update(self, params=None):
        import numpy as _np
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._tracked(params):
            cur = _np.asarray(p._value, dtype="float32")
            name = p.name or str(id(p))
            prev = self._shadow.get(name)
            self._shadow[name] = cur if prev is None else \
                d * prev + (1 - d) * cur

    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp
        for p in self._tracked():
            name = p.name or str(id(p))
            if name in self._shadow:
                self._backup[name] = p._value
                p._value = jnp.asarray(self._shadow[name]).astype(
                    p._value.dtype)
        return _ctx.nullcontext()

    def restore(self, executor=None):
        for p in self._tracked():
            name = p.name or str(id(p))
            if name in self._backup:
                p._value = self._backup.pop(name)


# ---- program / persistables serialization (static/io.py role) -----------
def serialize_program(feed_vars, fetch_vars, **kwargs):
    import pickle
    prog = default_main_program()
    return pickle.dumps({"name": prog.name if prog else "main",
                         "feeds": [getattr(v, "name", None) for v in feed_vars],
                         "fetches": [getattr(v, "name", None)
                                     for v in fetch_vars]})


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    import pickle
    import numpy as _np
    from .nn import _LAYER_SCOPE
    state = {}
    for key, layer in _LAYER_SCOPE.items():
        sd = getattr(layer, "state_dict", None)
        if sd is not None:
            state[key] = {k: _np.asarray(v._value)
                          for k, v in layer.state_dict().items()}
        elif hasattr(layer, "_value"):
            state[key] = _np.asarray(layer._value)
    return pickle.dumps(state)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    import pickle
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    import pickle
    import jax.numpy as jnp
    from .nn import _LAYER_SCOPE
    state = pickle.loads(data)
    for key, val in state.items():
        layer = _LAYER_SCOPE.get(key)
        if layer is None:
            continue
        if isinstance(val, dict):
            sd = layer.state_dict()
            for k, v in val.items():
                if k in sd:
                    sd[k]._value = jnp.asarray(v)
        elif hasattr(layer, "_value"):
            layer._value = jnp.asarray(val)
    return state


def save(program, model_path, protocol=4, **configs):
    """static.save: persist the legacy scope's persistables."""
    save_to_file(model_path + ".pdparams",
                 serialize_persistables([], []))


def load(program, model_path, executor=None, var_list=None):
    deserialize_persistables(program,
                             load_from_file(model_path + ".pdparams"))


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Inference-normalize (prune to feeds/fetches); Program.prune is the
    TPU-era form."""
    if hasattr(program, "prune"):
        try:
            return program.prune(feed_vars, fetch_vars)
        except Exception:
            return program
    return program


def load_program_state(model_path, var_list=None):
    import pickle
    return pickle.loads(load_from_file(model_path + ".pdparams"))


def set_program_state(program, state_dict):
    import pickle
    deserialize_persistables(program, pickle.dumps(state_dict))


def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    import os
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    from ..core.place import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..core.place import Place
    ids = device_ids if device_ids is not None else [0]
    return [Place("xpu", i) for i in ids]


def npu_places(device_ids=None):
    from ..core.place import NPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [NPUPlace(i) for i in ids]


def mlu_places(device_ids=None):
    from ..core.place import Place
    ids = device_ids if device_ids is not None else [0]
    return [Place("mlu", i) for i in ids]


# static Variable role is played by Tensor/InputSpec in the eager build
from ..core.tensor import Tensor as Variable  # noqa: E402,F401


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import numpy as _np
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    t = Tensor(jnp.full(tuple(shape), value, dtype=_np.dtype(dtype)))
    t.name = name
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    import numpy as _np
    import jax.numpy as jnp
    from ..core.tensor import Parameter
    if default_initializer is not None:
        v = default_initializer(tuple(shape), jnp.dtype(_np.dtype(dtype)))
    else:
        k = 1.0 / max(_np.sqrt(_np.prod(shape[:-1]) or 1), 1)
        v = jnp.asarray(_np.random.RandomState(0).uniform(
            -k, k, tuple(shape)).astype(_np.dtype(dtype)))
    p = Parameter(v, name=name)
    from .nn import _LAYER_SCOPE
    _LAYER_SCOPE[f"param:{name or id(p)}"] = p
    return p


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (fluid auc op surface): returns (auc, batch_auc, states)
    — here the exact batch AUC plus placeholder states."""
    import numpy as _np
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    p = _np.asarray(getattr(input, "_value", input))
    y = _np.asarray(getattr(label, "_value", label)).reshape(-1)
    score = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else p.reshape(-1)
    order = _np.argsort(-score)
    y_sorted = y[order]
    pos = y_sorted.sum()
    neg = len(y_sorted) - pos
    if pos == 0 or neg == 0:
        val = 0.0
    else:
        ranks = _np.empty(len(score))
        ranks[_np.argsort(score)] = _np.arange(1, len(score) + 1)
        val = float((ranks[y == 1].sum() - pos * (pos + 1) / 2) / (pos * neg))
    a = Tensor(jnp.asarray(val, jnp.float32))
    return a, a, []


def device_guard(device=None):
    import contextlib
    return contextlib.nullcontext()
