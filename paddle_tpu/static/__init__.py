"""paddle.static parity.

On TPU the "static graph" is a jax-traced XLA program (paddle_tpu.jit).
This module keeps the static-mode API surface: InputSpec, control flow
(static.nn.cond/while_loop), inference-model save/load, and a thin Executor
that runs @to_static functions — enough for reference static-style scripts
to port mechanically.
"""
from __future__ import annotations

_STATIC_MODE = [False]

from ..jit.input_spec import InputSpec  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from .program import Program, default_main_program  # noqa: E402,F401
from . import passes  # noqa: E402,F401
from .passes import apply_pass, list_passes, register_pass  # noqa: E402,F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    """Maps to jit.save on the captured layer (program == exported StableHLO).
    Pass layer= and input_spec= to use this entry point directly."""
    layer = kwargs.get("layer")
    if layer is not None:
        from ..jit.save_load import save as jsave
        return jsave(layer, path_prefix, input_spec=kwargs.get("input_spec", feed_vars))
    raise NotImplementedError(
        "static save: call paddle_tpu.jit.save(layer, path, input_spec) — the "
        "TPU build captures programs from Layers, not global default programs")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit.save_load import load as jload
    tl = jload(path_prefix)
    feed_names = [f"input_{i}" for i in range(len(tl._meta["input_specs"]))]
    fetch_names = ["output_0"]
    return tl, feed_names, fetch_names


class Executor:
    """Runs Programs / TranslatedLayers / @to_static functions.

    Reference Executor.run (`fluid/executor.py:611,1095`); here "run a
    program" means executing the compiled XLA artifact."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        import numpy as _np
        args = list(feed.values()) if isinstance(feed, dict) else (feed or [])
        if isinstance(program, Program):
            out = program.run(*[getattr(a, "_value", a) for a in args])
        elif callable(program):
            out = program(*args)
        else:
            raise NotImplementedError(
                "Executor.run expects a Program or callable on TPU")
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [_np.asarray(getattr(o, "_value", o)) for o in outs]

    def train_from_dataset(self, program, dataset, fetch_list=None,
                           print_period=100, debug=False):
        """Dataset-driven training loop (reference `executor.py:1731`
        `_run_from_dataset` -> C++ Trainer/DeviceWorker TrainFiles hot
        loop, SURVEY §3.5). `program` is a callable taking the batch dict
        {slot: array} and returning the loss; the loop host-side feeds
        batches exactly like MultiTrainer+HogwildWorker."""
        import numpy as _np
        losses = []
        for i, batch in enumerate(dataset):
            loss = program(batch)
            losses.append(float(getattr(loss, "_value", loss)))
            if debug and print_period and (i + 1) % print_period == 0:
                print(f"[train_from_dataset] batch {i + 1} "
                      f"loss {losses[-1]:.6f}")
        return losses


def default_startup_program():
    """Functional init: parameters are initialized at construction, so the
    startup program is empty — returned as an empty Program for parity."""
    return Program(lambda: (), [], name="startup")


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)
