"""Static-graph surface (paddle.static parity) — on TPU, "static graph" is a
jax-traced program; see paddle_tpu.jit. This module keeps the mode switch and
InputSpec so `enable_static()`-style code imports cleanly."""
_STATIC_MODE = [False]

from ..jit.input_spec import InputSpec  # noqa: F401,E402
