"""Device memory introspection (paddle.device.* surface).

Reference parity: `paddle/fluid/memory/stats.h` (StatRegistry's
max_memory_allocated / memory_allocated counters) and the
`paddle.device.cuda.max_memory_allocated` python surface.

TPU-first: XLA owns the allocator, so the authoritative numbers come
from the backend — `Device.memory_stats()` where the platform exposes it
(real TPU HBM pools), with a live-buffer walk (`jax.live_arrays`) as the
always-available fallback. A process-wide peak tracker is sampled at
every stats call and can be reset like the reference's counterpart.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax

__all__ = [
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "reset_max_memory_allocated", "device_count", "get_device",
]

_peak_bytes = [0]


def device_count() -> int:
    return jax.device_count()


def get_device() -> str:
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def _live_bytes(device=None) -> int:
    total = 0
    for a in jax.live_arrays():
        try:
            if device is not None and device not in {d.id for d in a.devices()}:
                continue
            total += a.nbytes
        except Exception:  # deleted/donated buffers race the walk
            continue
    return total


def memory_stats(device: Optional[int] = None) -> Dict[str, int]:
    """Allocator statistics for one device (default: device 0).

    Keys follow the reference StatRegistry naming: `allocated.current`,
    `allocated.peak`, plus backend pool stats (`bytes_in_use`,
    `peak_bytes_in_use`, ...) when the platform reports them."""
    d = jax.devices()[device or 0]
    out: Dict[str, int] = {}
    backend = None
    try:
        backend = d.memory_stats()
    except Exception:
        backend = None
    if backend:
        out.update({k: int(v) for k, v in backend.items()
                    if isinstance(v, (int, float))})
    live = _live_bytes(d.id)
    _peak_bytes[0] = max(_peak_bytes[0], live,
                         int(out.get("peak_bytes_in_use", 0)))
    out["allocated.current"] = int(out.get("bytes_in_use", live))
    out["allocated.peak"] = _peak_bytes[0]
    return out


def memory_allocated(device: Optional[int] = None) -> int:
    return memory_stats(device)["allocated.current"]


def max_memory_allocated(device: Optional[int] = None) -> int:
    return memory_stats(device)["allocated.peak"]


def reset_max_memory_allocated(device: Optional[int] = None) -> None:
    _peak_bytes[0] = 0
    memory_stats(device)
