"""Device memory introspection (paddle.device.* surface).

Reference parity: `paddle/fluid/memory/stats.h` (StatRegistry's
max_memory_allocated / memory_allocated counters) and the
`paddle.device.cuda.max_memory_allocated` python surface.

TPU-first: XLA owns the allocator, so the authoritative numbers come
from the backend — `Device.memory_stats()` where the platform exposes it
(real TPU HBM pools), with a per-shard live-buffer walk
(`jax.live_arrays` -> addressable_shards) as the always-available
fallback. Peaks are tracked PER DEVICE and are resettable like the
reference counters; after a reset the peak is the max of sampled
footprints (XLA's own process-lifetime peak cannot be reset, so it is
only folded in before the first reset).
"""
from __future__ import annotations

from typing import Dict, Optional

import jax

from ..core.place import device_count, get_device  # noqa: F401  (one surface)

__all__ = [
    "memory_stats", "memory_allocated", "max_memory_allocated",
    "reset_max_memory_allocated", "device_count", "get_device",
]

_peaks: Dict[int, int] = {}         # device id -> tracked peak bytes
_reset_called: Dict[int, bool] = {}  # device id -> reset happened


def _live_bytes(device_id: int) -> int:
    """Bytes actually resident on `device_id`: sums the per-device SHARD
    sizes, so sharded arrays count 1/n per device and replicated arrays
    count their full size on every device. Shard sizes are derived from
    each array's sharding — touching `a.addressable_shards` would
    MATERIALIZE one child ArrayImpl per shard into `jax.live_arrays()`
    and double every later walk (obs.memory dedups by buffer the same
    way)."""
    from ..obs import memory as _mem
    total = 0
    seen = set()
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            key = _mem._buffer_key(a)
            if key in seen:
                continue
            seen.add(key)
            nb, devs = _mem._per_device_bytes(a)
            if device_id in devs:
                total += nb
        except Exception:  # deleted/donated buffers race the walk
            continue
    return total


def memory_stats(device: Optional[int] = None) -> Dict[str, int]:
    """Allocator statistics for one device (default: device 0).

    Keys follow the reference StatRegistry naming: `allocated.current`,
    `allocated.peak`, plus backend pool stats (`bytes_in_use`,
    `peak_bytes_in_use`, ...) when the platform reports them."""
    d = jax.devices()[device or 0]
    out: Dict[str, int] = {}
    try:
        backend = d.memory_stats()
    except Exception:
        backend = None
    if backend:
        out.update({k: int(v) for k, v in backend.items()
                    if isinstance(v, (int, float))})
    cur = int(out["bytes_in_use"]) if "bytes_in_use" in out \
        else _live_bytes(d.id)
    peak = max(_peaks.get(d.id, 0), cur)
    if not _reset_called.get(d.id):
        # XLA's pool peak covers allocations our sampling missed — but it
        # is process-lifetime and unresettable, so only before a reset
        peak = max(peak, int(out.get("peak_bytes_in_use", 0)))
    _peaks[d.id] = peak
    out["allocated.current"] = cur
    out["allocated.peak"] = peak
    return out


def memory_allocated(device: Optional[int] = None) -> int:
    return memory_stats(device)["allocated.current"]


def max_memory_allocated(device: Optional[int] = None) -> int:
    return memory_stats(device)["allocated.peak"]


def reset_max_memory_allocated(device: Optional[int] = None) -> None:
    d = jax.devices()[device or 0]
    _reset_called[d.id] = True
    _peaks[d.id] = 0
    memory_stats(device)
