"""paddle.distribution parity: Normal/Uniform/Categorical/Bernoulli/... .

Reference parity: `python/paddle/distribution/` (Distribution base with
sample/log_prob/entropy/kl_divergence). log_prob/entropy route through the
autograd tape (run_op) so dygraph gradients flow to distribution parameters.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op, to_arr


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        import paddle_tpu as paddle
        return paddle.exp(self.log_prob(value))


def _t(x):
    return ensure_tensor(x, dtype=jnp.float32) if not isinstance(x, Tensor) else x


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(tuple(self.loc.shape),
                                                        tuple(self.scale.shape)))
        z = jax.random.normal(rnd.next_key(), shp)
        return run_op(lambda m, s: m + s * z, [self.loc, self.scale], "normal_sample")

    rsample = sample

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda m, s, x: -((x - m) ** 2) / (2 * s * s) - jnp.log(s)
            - 0.5 * math.log(2 * math.pi),
            [self.loc, self.scale, v], "normal_log_prob")

    def entropy(self):
        return run_op(
            lambda m, s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)
            + jnp.zeros_like(m),
            [self.loc, self.scale], "normal_entropy")

    def kl_divergence(self, other):
        return run_op(
            lambda m1, s1, m2, s2: 0.5 * ((s1 / s2) ** 2 + ((m1 - m2) / s2) ** 2
                                          - 1 - 2 * jnp.log(s1 / s2)),
            [self.loc, self.scale, other.loc, other.scale], "normal_kl")


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(tuple(self.low.shape),
                                                        tuple(self.high.shape)))
        u = jax.random.uniform(rnd.next_key(), shp)
        return run_op(lambda lo, hi: lo + (hi - lo) * u, [self.low, self.high],
                      "uniform_sample")

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda lo, hi, x: jnp.where((x >= lo) & (x < hi), -jnp.log(hi - lo),
                                        -jnp.inf),
            [self.low, self.high, v], "uniform_log_prob")

    def entropy(self):
        return run_op(lambda lo, hi: jnp.log(hi - lo), [self.low, self.high],
                      "uniform_entropy")


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _t(probs)

    def sample(self, shape=()):
        p = self.probs_._value
        shp = tuple(shape) + tuple(p.shape)
        return Tensor(jax.random.bernoulli(rnd.next_key(), p, shp).astype(jnp.float32))

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda p, x: x * jnp.log(jnp.clip(p, 1e-7, 1 - 1e-7))
            + (1 - x) * jnp.log1p(-jnp.clip(p, 1e-7, 1 - 1e-7)),
            [self.probs_, v], "bernoulli_log_prob")

    def entropy(self):
        def f(p):
            pc = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(pc * jnp.log(pc) + (1 - pc) * jnp.log1p(-pc))

        return run_op(f, [self.probs_], "bernoulli_entropy")


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def sample(self, shape=()):
        lg = self.logits._value
        return Tensor(jax.random.categorical(rnd.next_key(), lg,
                                             shape=tuple(shape) + tuple(lg.shape[:-1])))

    def log_prob(self, value):
        ids = ensure_tensor(value)._value.astype(jnp.int32)

        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]

        return run_op(f, [self.logits], "categorical_log_prob")

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

        return run_op(f, [self.logits], "categorical_entropy")


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _t(probs)

    def sample(self, shape=()):
        p = self.probs_._value
        draws = jax.random.categorical(
            rnd.next_key(), jnp.log(p), shape=tuple(shape) + (self.total_count,))
        k = p.shape[-1]
        return Tensor(jax.nn.one_hot(draws, k).sum(axis=-2))


def kl_divergence(p, q):
    if hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(f"kl({type(p).__name__}, {type(q).__name__})")
