"""paddle.distribution parity: Normal/Uniform/Categorical/Bernoulli/... .

Reference parity: `python/paddle/distribution/` (Distribution base with
sample/log_prob/entropy/kl_divergence). log_prob/entropy route through the
autograd tape (run_op) so dygraph gradients flow to distribution parameters.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op, to_arr


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        import paddle_tpu as paddle
        return paddle.exp(self.log_prob(value))


def _t(x):
    return ensure_tensor(x, dtype=jnp.float32) if not isinstance(x, Tensor) else x


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(tuple(self.loc.shape),
                                                        tuple(self.scale.shape)))
        z = jax.random.normal(rnd.next_key(), shp)
        return run_op(lambda m, s: m + s * z, [self.loc, self.scale], "normal_sample")

    rsample = sample

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda m, s, x: -((x - m) ** 2) / (2 * s * s) - jnp.log(s)
            - 0.5 * math.log(2 * math.pi),
            [self.loc, self.scale, v], "normal_log_prob")

    def entropy(self):
        return run_op(
            lambda m, s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)
            + jnp.zeros_like(m),
            [self.loc, self.scale], "normal_entropy")

    def kl_divergence(self, other):
        return run_op(
            lambda m1, s1, m2, s2: 0.5 * ((s1 / s2) ** 2 + ((m1 - m2) / s2) ** 2
                                          - 1 - 2 * jnp.log(s1 / s2)),
            [self.loc, self.scale, other.loc, other.scale], "normal_kl")


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=(), seed=0):
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(tuple(self.low.shape),
                                                        tuple(self.high.shape)))
        u = jax.random.uniform(rnd.next_key(), shp)
        return run_op(lambda lo, hi: lo + (hi - lo) * u, [self.low, self.high],
                      "uniform_sample")

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda lo, hi, x: jnp.where((x >= lo) & (x < hi), -jnp.log(hi - lo),
                                        -jnp.inf),
            [self.low, self.high, v], "uniform_log_prob")

    def entropy(self):
        return run_op(lambda lo, hi: jnp.log(hi - lo), [self.low, self.high],
                      "uniform_entropy")


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _t(probs)

    def sample(self, shape=()):
        p = self.probs_._value
        shp = tuple(shape) + tuple(p.shape)
        return Tensor(jax.random.bernoulli(rnd.next_key(), p, shp).astype(jnp.float32))

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda p, x: x * jnp.log(jnp.clip(p, 1e-7, 1 - 1e-7))
            + (1 - x) * jnp.log1p(-jnp.clip(p, 1e-7, 1 - 1e-7)),
            [self.probs_, v], "bernoulli_log_prob")

    def entropy(self):
        def f(p):
            pc = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(pc * jnp.log(pc) + (1 - pc) * jnp.log1p(-pc))

        return run_op(f, [self.probs_], "bernoulli_entropy")


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def sample(self, shape=()):
        lg = self.logits._value
        return Tensor(jax.random.categorical(rnd.next_key(), lg,
                                             shape=tuple(shape) + tuple(lg.shape[:-1])))

    def log_prob(self, value):
        ids = ensure_tensor(value)._value.astype(jnp.int32)

        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]

        return run_op(f, [self.logits], "categorical_log_prob")

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

        return run_op(f, [self.logits], "categorical_entropy")


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _t(probs)

    def sample(self, shape=()):
        p = self.probs_._value
        draws = jax.random.categorical(
            rnd.next_key(), jnp.log(p), shape=tuple(shape) + (self.total_count,))
        k = p.shape[-1]
        return Tensor(jax.nn.one_hot(draws, k).sum(axis=-2))


def kl_divergence(p, q):
    # registered pairwise rules first (register_kl), walking the MROs the
    # way the reference's dispatch does
    for kp in type(p).__mro__:
        for kq in type(q).__mro__:
            fn = _KL_REGISTRY.get((kp, kq))
            if fn is not None:
                return fn(p, q)
    if hasattr(p, "kl_divergence"):
        return p.kl_divergence(q)
    raise NotImplementedError(f"kl({type(p).__name__}, {type(q).__name__})")


class Beta(Distribution):
    """Reference `python/paddle/distribution/beta.py` parity."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)

    def sample(self, shape=()):
        a, b = self.alpha._value, self.beta._value
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(a.shape, b.shape))
        k1, k2 = jax.random.split(rnd.next_key())
        ga = jax.random.gamma(k1, jnp.broadcast_to(a, shp))
        gb = jax.random.gamma(k2, jnp.broadcast_to(b, shp))
        return Tensor(ga / (ga + gb))

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda a, b, x: (a - 1) * jnp.log(x) + (b - 1) * jnp.log1p(-x)
            - (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
               - jax.scipy.special.gammaln(a + b)),
            [self.alpha, self.beta, v], "beta_log_prob")

    def mean(self):
        return run_op(lambda a, b: a / (a + b), [self.alpha, self.beta],
                      "beta_mean")

    def entropy(self):
        def f(a, b):
            lnB = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                   - jax.scipy.special.gammaln(a + b))
            dg = jax.scipy.special.digamma
            return (lnB - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return run_op(f, [self.alpha, self.beta], "beta_entropy")


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)

    def sample(self, shape=()):
        c, r = self.concentration._value, self.rate._value
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(c.shape, r.shape))
        g = jax.random.gamma(rnd.next_key(), jnp.broadcast_to(c, shp))
        return Tensor(g / jnp.broadcast_to(r, shp))

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda c, r, x: c * jnp.log(r) + (c - 1) * jnp.log(x) - r * x
            - jax.scipy.special.gammaln(c),
            [self.concentration, self.rate, v], "gamma_log_prob")

    def mean(self):
        return run_op(lambda c, r: c / r, [self.concentration, self.rate],
                      "gamma_mean")


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=()):
        m, s = self.loc._value, self.scale._value
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(m.shape, s.shape))
        z = jax.random.laplace(rnd.next_key(), shp)
        return run_op(lambda mm, ss: mm + ss * z, [self.loc, self.scale],
                      "laplace_sample")

    rsample = sample

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda m, s, x: -jnp.abs(x - m) / s - jnp.log(2 * s),
            [self.loc, self.scale, v], "laplace_log_prob")

    def entropy(self):
        return run_op(lambda m, s: 1 + jnp.log(2 * s) + jnp.zeros_like(m),
                      [self.loc, self.scale], "laplace_entropy")


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self._base = Normal(loc, scale)

    def sample(self, shape=()):
        import paddle_tpu as paddle
        return paddle.exp(self._base.sample(shape))

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda m, s, x: -((jnp.log(x) - m) ** 2) / (2 * s * s)
            - jnp.log(x * s) - 0.5 * math.log(2 * math.pi),
            [self.loc, self.scale, v], "lognormal_log_prob")


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=()):
        m, s = self.loc._value, self.scale._value
        shp = tuple(shape) + tuple(jnp.broadcast_shapes(m.shape, s.shape))
        z = jax.random.gumbel(rnd.next_key(), shp)
        return run_op(lambda mm, ss: mm + ss * z, [self.loc, self.scale],
                      "gumbel_sample")

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(
            lambda m, s, x: -(x - m) / s - jnp.exp(-(x - m) / s) - jnp.log(s),
            [self.loc, self.scale, v], "gumbel_log_prob")


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k >= 0 (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs_ = _t(probs)

    def sample(self, shape=()):
        p = self.probs_._value
        shp = tuple(shape) + tuple(p.shape)
        u = jax.random.uniform(rnd.next_key(), shp, minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-p)))

    def log_prob(self, value):
        v = ensure_tensor(value)
        return run_op(lambda p, k: k * jnp.log1p(-p) + jnp.log(p),
                      [self.probs_, v], "geometric_log_prob")


class ExponentialFamily(Distribution):
    """Marker base (reference exponential_family.py) — entropy via the
    Bregman identity is specialized in subclasses here."""


class TransformedDistribution(Distribution):
    """y = transform(x), x ~ base (reference transformed_distribution.py);
    transform provides forward(x), inverse(y), log_det_jacobian(x)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = ensure_tensor(value)
        ldj_sum = None
        x = y
        for t in reversed(self.transforms):
            x = t.inverse(x)
            ldj = t.log_det_jacobian(x)
            ldj_sum = ldj if ldj_sum is None else ldj_sum + ldj
        lp = self.base.log_prob(x)
        return lp - ldj_sum if ldj_sum is not None else lp


class ExpTransform:
    def forward(self, x):
        import paddle_tpu as paddle
        return paddle.exp(x)

    def inverse(self, y):
        import paddle_tpu as paddle
        return paddle.log(y)

    def log_det_jacobian(self, x):
        return x  # log|d e^x / dx| = x


class AffineTransform:
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return run_op(lambda m, s, v: m + s * v, [self.loc, self.scale,
                                                  ensure_tensor(x)], "affine_fwd")

    def inverse(self, y):
        return run_op(lambda m, s, v: (v - m) / s, [self.loc, self.scale,
                                                    ensure_tensor(y)], "affine_inv")

    def log_det_jacobian(self, x):
        return run_op(lambda m, s, v: jnp.broadcast_to(jnp.log(jnp.abs(s)),
                                                       v.shape),
                      [self.loc, self.scale, ensure_tensor(x)], "affine_ldj")


class Dirichlet(Distribution):
    """Dirichlet(concentration) (`distribution/dirichlet.py`)."""

    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)

    @property
    def mean(self):
        from ..ops._dispatch import run_op as _run
        return _run(lambda c: c / jnp.sum(c, -1, keepdims=True),
                    [self.concentration], "dirichlet_mean")

    @property
    def variance(self):
        from ..ops._dispatch import run_op as _run

        def f(c):
            a0 = jnp.sum(c, -1, keepdims=True)
            m = c / a0
            return m * (1 - m) / (a0 + 1)

        return _run(f, [self.concentration], "dirichlet_var")

    def sample(self, shape=()):
        from ..core import random as rnd
        import jax as _jax
        key = rnd.next_key()
        c = self.concentration._value
        out = _jax.random.dirichlet(key, c, tuple(shape) + c.shape[:-1])
        return Tensor(out)

    def log_prob(self, value):
        from ..ops._dispatch import run_op as _run
        import jax as _jax

        def f(c, v):
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + _jax.scipy.special.gammaln(jnp.sum(c, -1))
                    - jnp.sum(_jax.scipy.special.gammaln(c), -1))

        return _run(f, [self.concentration, _t(value)], "dirichlet_logp")

    def entropy(self):
        from ..ops._dispatch import run_op as _run
        import jax as _jax

        def f(c):
            a0 = jnp.sum(c, -1)
            k = c.shape[-1]
            lnB = jnp.sum(_jax.scipy.special.gammaln(c), -1) \
                - _jax.scipy.special.gammaln(a0)
            dg = _jax.scipy.special.digamma
            return (lnB + (a0 - k) * dg(a0)
                    - jnp.sum((c - 1) * dg(c), -1))

        return _run(f, [self.concentration], "dirichlet_entropy")


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL rule consumed by kl_divergence
    (`distribution/kl.py` register_kl)."""

    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return deco
