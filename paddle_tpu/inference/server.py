"""Predictor service — the server side of the C inference API.

Reference parity: the deployment role of `inference/capi_exp/` +
`goapi/`: C/Go apps run inference against a stable ABI. Here the ABI is a
binary tensor protocol (see csrc/predict_capi.cpp) served by the process
that owns the TPU runtime. Connection handler threads no longer run the
Predictor themselves (the seed's thread-per-connection loop collapsed TPU
throughput to batch-1 latency): every request is submitted to the
`paddle_tpu.serving.ServingEngine`, which coalesces concurrent requests
into padded shape-bucket batches, enforces deadlines and queue-depth
backpressure, and drives the jitted Predictor from its worker loop.

Wire protocol (little-endian), on top of csrc/predict_capi.cpp's framing:
  trace:     u32 'PDTC', 26-byte trace context (OPTIONAL prefix a tracing
             client sends immediately before its request frame; absence
             means "no trace" — untraced exchanges are byte-identical to
             the pre-PDTC protocol, so old peers interoperate)
  request:   u32 'PDRQ', u32 n_tensors, tensors
  deadline:  u32 'PDRD', u32 deadline_ms, u32 n_tensors, tensors
  health:    u32 'PDHQ' (no body)
  response:  u32 'PDRS', u8 status;
             status 0: u32 n_tensors + tensors ('PDHQ': u32 len + JSON)
             status 1 (error) / 2 (overloaded, retryable) /
             status 3 (deadline expired): u32 len + utf-8 message

Under `FLAGS_trace` one request produces one trace: the client's
`client.send` root span, the server's `serving.request` child carried
over by 'PDTC', the engine's queue_wait/batch/dispatch spans under it,
and `serving.reply` around the response write (obs/trace.py).
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

_REQ_MAGIC = 0x50445251       # 'PDRQ'
_REQ_DEADLINE_MAGIC = 0x50445244  # 'PDRD': u32 deadline_ms precedes count
_HEALTH_MAGIC = 0x50444851    # 'PDHQ': health/stats probe, no tensor body
_RESP_MAGIC = 0x50445253      # 'PDRS'
_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                np.dtype(np.int64): 2}
_MAX_NDIM = 8
_MAX_TENSOR_BYTES = 1 << 32  # sanity cap against corrupt headers

from ..obs import trace as _trace  # noqa: E402
from ..serving import (  # noqa: E402
    DeadlineExceededError, EngineConfig, ServerOverloadedError, ServingEngine)
from ..utils.net import (  # noqa: E402
    STATUS_DEADLINE, STATUS_ERROR, STATUS_OK, STATUS_OVERLOADED,
    TRACE_MAGIC as _TRACE_MAGIC, recv_exact as _recv_exact,
    recv_trace_frame, send_status_frame, send_trace_frame)


def _read_tensor(conn, deadline: Optional[float] = None) -> np.ndarray:
    dt, ndim = struct.unpack("<II", _recv_exact(conn, 8, deadline))
    if dt not in _DTYPES or ndim > _MAX_NDIM:
        raise ValueError(f"bad tensor header dtype={dt} ndim={ndim}")
    dims = struct.unpack(f"<{ndim}q", _recv_exact(conn, 8 * ndim, deadline))
    dtype = _DTYPES[dt]
    if any(d < 0 for d in dims):
        raise ValueError(f"bad tensor dims {dims}")
    count = int(np.prod(dims)) if dims else 1
    nbytes = count * dtype().itemsize
    if nbytes > _MAX_TENSOR_BYTES:
        raise ValueError(f"tensor payload {nbytes} bytes exceeds cap")
    payload = _recv_exact(conn, nbytes, deadline)
    return np.frombuffer(payload, dtype).reshape(dims).copy()


def _write_tensor(conn, arr: np.ndarray):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _DTYPE_CODES:
        arr = arr.astype(np.float32)
    conn.sendall(struct.pack("<II", _DTYPE_CODES[arr.dtype], arr.ndim)
                 + struct.pack(f"<{arr.ndim}q", *arr.shape)
                 + arr.tobytes())


class PredictorServer:
    """Serve a Predictor (or any callable of numpy arrays) over the C-API
    wire protocol, with the ServingEngine between connections and the
    accelerator. Pass `engine=` to share a pre-configured engine, or
    `engine_config=` to tune the built-in one; the default reads the
    FLAGS_serving_* flags."""

    # handler threads park on the response future at most this long — a
    # wedged predictor must not leak handler threads forever
    _RESULT_TIMEOUT_S = 600.0
    # once a request's magic arrives, the REST of the frame must follow
    # within this budget — a client that stalls (not closes) mid-request
    # must not pin a handler thread forever (idle BETWEEN requests is
    # fine and unbounded)
    _READ_TIMEOUT_S = 60.0

    def __init__(self, predictor, host="127.0.0.1", port=0,
                 engine: Optional[ServingEngine] = None,
                 engine_config: Optional[EngineConfig] = None):
        self.predictor = predictor
        self.engine = engine or ServingEngine(predictor, engine_config)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self.engine.start()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle_one(self, conn) -> bool:
        """One request/response exchange; False = close the connection."""
        magic, = struct.unpack("<I", _recv_exact(conn, 4))
        tctx = None
        if magic == _TRACE_MAGIC:
            # OPTIONAL trace prefix: consume the context, then read the
            # real request magic that follows
            read_deadline = time.monotonic() + self._READ_TIMEOUT_S
            tctx = recv_trace_frame(conn, read_deadline)
            magic, = struct.unpack("<I", _recv_exact(conn, 4,
                                                     read_deadline))
        if magic == _HEALTH_MAGIC:
            payload = json.dumps(self.engine.stats(),
                                 default=str).encode()
            conn.sendall(struct.pack("<IB", _RESP_MAGIC, STATUS_OK)
                         + struct.pack("<I", len(payload)) + payload)
            return True
        # serving.request: the server-side root of this request's trace,
        # parented on the client's wire context; closes with the same
        # status the wire response carries (absence of 'PDTC' -> no-op)
        rspan = _trace.server_span("serving.request", tctx)
        try:
            keep = self._handle_request(conn, magic, rspan)
        except BaseException as e:
            rspan.end(status=_trace.STATUS_ERROR,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            raise
        rspan.end()  # idempotent: error paths already set their status
        return keep

    def _handle_request(self, conn, magic, rspan) -> bool:
        read_deadline = time.monotonic() + self._READ_TIMEOUT_S
        deadline_ms = None
        if magic == _REQ_DEADLINE_MAGIC:
            dl, = struct.unpack("<I", _recv_exact(conn, 4, read_deadline))
            deadline_ms = float(dl) if dl else None
        elif magic != _REQ_MAGIC:
            rspan.end(status=_trace.STATUS_ERROR, error="bad magic")
            return False  # protocol violation: drop the connection
        n, = struct.unpack("<I", _recv_exact(conn, 4, read_deadline))
        try:
            inputs = [_read_tensor(conn, read_deadline) for _ in range(n)]
        except ValueError as e:
            # header was bad: stream unrecoverable, report + close
            rspan.end(status=_trace.STATUS_ERROR, error=str(e)[:200])
            send_status_frame(conn, STATUS_ERROR, str(e))
            return False
        try:
            fut = self.engine.submit(inputs, deadline_ms=deadline_ms,
                                     trace_ctx=rspan.ctx())
            outs = fut.result(timeout=self._RESULT_TIMEOUT_S)
        except ServerOverloadedError as e:
            rspan.end(status=_trace.STATUS_REJECTED)
            send_status_frame(conn, STATUS_OVERLOADED, str(e))
            return True
        except DeadlineExceededError as e:
            rspan.end(status=_trace.STATUS_DEADLINE)
            send_status_frame(conn, STATUS_DEADLINE, str(e))
            return True
        except Exception as e:  # surface model errors to the C app
            rspan.end(status=_trace.STATUS_ERROR,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            send_status_frame(conn, STATUS_ERROR, str(e))
            return True
        with _trace.server_span("serving.reply", rspan.ctx(),
                                attrs={"n_outputs": len(outs)}):
            conn.sendall(struct.pack("<IBI", _RESP_MAGIC, STATUS_OK,
                                     len(outs)))
            for o in outs:
                _write_tensor(conn, np.asarray(o))
        return True

    def _handle(self, conn):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while self._handle_one(conn):
                pass
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            conn.close()

    def stats(self):
        """Engine health snapshot (what the 'PDHQ' wire probe returns):
        queue/bucket/deadline counters plus `warm_start_ms` and the
        `compile_cache` hit/miss stats, so a fleet dashboard can tell a
        replica that warm-started from the persistent executable cache
        from one that paid its own compiles."""
        return self.engine.stats()

    def stop(self, drain: bool = True):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)
        self.engine.stop(drain=drain)


class PredictorClient:
    """Minimal python-side client of the wire protocol (the C client in
    csrc/predict_capi.cpp is the production ABI; this one drives tests and
    python tooling — including the health probe)."""

    def __init__(self, host, port, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # wire status -> terminal span status for the client.send root span
    _SPAN_STATUS = {STATUS_OK: _trace.STATUS_OK,
                    STATUS_ERROR: _trace.STATUS_ERROR,
                    STATUS_OVERLOADED: _trace.STATUS_REJECTED,
                    STATUS_DEADLINE: _trace.STATUS_DEADLINE}

    def run(self, arrays, deadline_ms: Optional[float] = None):
        """Returns (status, payload): payload is the output list on
        STATUS_OK, else the server's utf-8 message.

        Under `FLAGS_trace` each call mints a new trace: a `client.send`
        root span whose context rides a 'PDTC' prefix frame, so the
        server (and engine) spans land in the SAME trace. Tracing off =
        byte-identical frames to the pre-PDTC protocol."""
        with _trace.span("client.send",
                         attrs={"n_tensors": len(arrays)}) as sp:
            if sp.trace_id is not None:
                send_trace_frame(self._sock, sp.ctx())
            if deadline_ms is not None:
                hdr = struct.pack("<III", _REQ_DEADLINE_MAGIC,
                                  int(deadline_ms), len(arrays))
            else:
                hdr = struct.pack("<II", _REQ_MAGIC, len(arrays))
            self._sock.sendall(hdr)
            for a in arrays:
                _write_tensor(self._sock, np.asarray(a))
            magic, status = struct.unpack("<IB",
                                          _recv_exact(self._sock, 5))
            if magic != _RESP_MAGIC:
                raise ConnectionError(f"bad response magic {magic:#x}")
            if status != STATUS_OK:
                ln, = struct.unpack("<I", _recv_exact(self._sock, 4))
                msg = _recv_exact(self._sock, ln).decode()
                sp.end(status=self._SPAN_STATUS.get(
                    status, _trace.STATUS_ERROR))
                return status, msg
            n, = struct.unpack("<I", _recv_exact(self._sock, 4))
            return status, [_read_tensor(self._sock) for _ in range(n)]

    def health(self) -> dict:
        self._sock.sendall(struct.pack("<I", _HEALTH_MAGIC))
        magic, status = struct.unpack("<IB", _recv_exact(self._sock, 5))
        if magic != _RESP_MAGIC or status != STATUS_OK:
            raise ConnectionError("bad health response")
        ln, = struct.unpack("<I", _recv_exact(self._sock, 4))
        return json.loads(_recv_exact(self._sock, ln).decode())

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
