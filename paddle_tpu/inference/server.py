"""Predictor service — the server side of the C inference API.

Reference parity: the deployment role of `inference/capi_exp/` +
`goapi/`: C/Go apps run inference against a stable ABI. Here the ABI is a
binary tensor protocol (see csrc/predict_capi.cpp) served by the process
that owns the TPU runtime. Connection handler threads no longer run the
Predictor themselves (the seed's thread-per-connection loop collapsed TPU
throughput to batch-1 latency): every request is submitted to the
`paddle_tpu.serving.ServingEngine`, which coalesces concurrent requests
into padded shape-bucket batches, enforces deadlines and queue-depth
backpressure, and drives the jitted Predictor from its worker loop.

Wire protocol (little-endian), on top of csrc/predict_capi.cpp's framing:
  trace:     u32 'PDTC', 26-byte trace context (OPTIONAL prefix a tracing
             client sends immediately before its request frame; absence
             means "no trace" — untraced exchanges are byte-identical to
             the pre-PDTC protocol, so old peers interoperate)
  model:     u32 'PDMQ', u32 len, utf-8 model name (OPTIONAL prefix:
             routes the following request to a named hosted model on a
             multi-model replica; absence = the default model)
  request:   u32 'PDRQ', u32 n_tensors, tensors
  deadline:  u32 'PDRD', u32 deadline_ms, u32 n_tensors, tensors
  health:    u32 'PDHQ' (no body)
  drain:     u32 'PDDR' (no body) — graceful drain: the listening port
             closes, queued+in-flight work completes, the replica
             deregisters; answers status 0 + u32 len + JSON drain report
  model ctl: u32 'PDMV', u32 len, JSON {op: reload|rollback, model};
             answers status 0 + u32 len + JSON {ok, version, ...}
  stream:    u32 'PDSQ', u32 max_new_tokens, u32 deadline_ms (0 = none),
             u32 n_tensors (=1), one 1-D i32 prompt tensor — continuous-
             batching LLM generation (serving/llm.py, pass `llm_engine=`).
             Each generated token is pushed the moment the scheduler
             emits it as u32 'PDST' + u32 index + i32 token; the exchange
             then ends in a standard 'PDRS' frame (status 0 + the full
             token tensor, or error/overloaded/deadline + message), so a
             non-streaming caller can skip 'PDST' frames and read the
             terminal response like any other request
  response:  u32 'PDRS', u8 status;
             status 0: u32 n_tensors + tensors ('PDHQ': u32 len + JSON)
             status 1 (error) / 2 (overloaded/draining, retryable) /
             status 3 (deadline expired): u32 len + utf-8 message

Under `FLAGS_trace` one request produces one trace: the client's
`client.send` root span, the server's `serving.request` child carried
over by 'PDTC', the engine's queue_wait/batch/dispatch spans under it,
and `serving.reply` around the response write (obs/trace.py).
"""
from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
from typing import Dict, Optional

import numpy as np

# The serving codec reads/writes frames on connections the substrate
# (utils/net.py RpcChannel / secure_server) owns and hands out — those
# raw send/recv calls are the plane's wire format, not a bypass.
# tpu-lint: disable=raw-socket

_REQ_MAGIC = 0x50445251       # 'PDRQ'
_REQ_DEADLINE_MAGIC = 0x50445244  # 'PDRD': u32 deadline_ms precedes count
_HEALTH_MAGIC = 0x50444851    # 'PDHQ': health/stats probe, no tensor body
_RESP_MAGIC = 0x50445253      # 'PDRS'
_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                np.dtype(np.int64): 2}
_MAX_NDIM = 8
_MAX_TENSOR_BYTES = 1 << 32  # sanity cap against corrupt headers
_MAX_NAME_LEN = 1 << 16      # cap on control-frame string bodies

from ..core import flags as _flags  # noqa: E402
from ..obs import trace as _trace  # noqa: E402
from ..serving import (  # noqa: E402
    DeadlineExceededError, EngineConfig, EngineStoppedError,
    ServerOverloadedError, ServingEngine)
from ..utils import net as _net  # noqa: E402
from ..utils.net import (  # noqa: E402
    DRAIN_MAGIC as _DRAIN_MAGIC, MODEL_CTL_MAGIC as _MODEL_CTL_MAGIC,
    MODEL_MAGIC as _MODEL_MAGIC, STATUS_DEADLINE, STATUS_ERROR, STATUS_OK,
    STATUS_OVERLOADED, STREAM_MAGIC as _STREAM_MAGIC,
    STREAM_REQ_MAGIC as _STREAM_REQ_MAGIC, TRACE_MAGIC as _TRACE_MAGIC,
    recv_exact as _recv_exact, recv_trace_frame, send_status_frame,
    send_trace_frame)
from ..utils import syncwatch as _syncwatch  # noqa: E402


def _read_tensor(conn, deadline: Optional[float] = None) -> np.ndarray:
    dt, ndim = struct.unpack("<II", _recv_exact(conn, 8, deadline))
    if dt not in _DTYPES or ndim > _MAX_NDIM:
        raise ValueError(f"bad tensor header dtype={dt} ndim={ndim}")
    dims = struct.unpack(f"<{ndim}q", _recv_exact(conn, 8 * ndim, deadline))
    dtype = _DTYPES[dt]
    if any(d < 0 for d in dims):
        raise ValueError(f"bad tensor dims {dims}")
    count = int(np.prod(dims)) if dims else 1
    nbytes = count * dtype().itemsize
    if nbytes > _MAX_TENSOR_BYTES:
        raise ValueError(f"tensor payload {nbytes} bytes exceeds cap")
    payload = _recv_exact(conn, nbytes, deadline)
    return np.frombuffer(payload, dtype).reshape(dims).copy()


def _write_tensor(conn, arr: np.ndarray):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _DTYPE_CODES:
        arr = arr.astype(np.float32)
    conn.sendall(struct.pack("<II", _DTYPE_CODES[arr.dtype], arr.ndim)
                 + struct.pack(f"<{arr.ndim}q", *arr.shape)
                 + arr.tobytes())


class PredictorServer:
    """Serve a Predictor (or any callable of numpy arrays) over the C-API
    wire protocol, with the ServingEngine between connections and the
    accelerator. Pass `engine=` to share a pre-configured engine, or
    `engine_config=` to tune the built-in one; the default reads the
    FLAGS_serving_* flags."""

    # handler threads park on the response future at most this long — a
    # wedged predictor must not leak handler threads forever
    _RESULT_TIMEOUT_S = 600.0
    # once a request's magic arrives, the REST of the frame must follow
    # within this budget — a client that stalls (not closes) mid-request
    # must not pin a handler thread forever (idle BETWEEN requests is
    # fine and unbounded)
    _READ_TIMEOUT_S = 60.0

    def __init__(self, predictor, host="127.0.0.1", port=0,
                 engine: Optional[ServingEngine] = None,
                 engine_config: Optional[EngineConfig] = None,
                 llm_engine=None, on_drain=None, on_model_ctl=None,
                 stats_extra=None):
        self.predictor = predictor
        self.engine = engine or ServingEngine(predictor, engine_config)
        # continuous-batching generation plane (serving/llm.py): serves
        # 'PDSQ' streaming requests when present; absent -> 'PDSQ' gets a
        # clean STATUS_ERROR and the batch protocol is untouched
        self.llm_engine = llm_engine
        # named hosted models (multi-model replicas): 'PDMQ'-selected
        # requests route to engines[name]; the unnamed default stays
        # `self.engine` so single-model callers are untouched
        self.engines: Dict[str, ServingEngine] = {}
        # fleet hooks, all optional: `on_drain()` runs between the port
        # closing and the engines draining (the agent deregisters its
        # lease there); `on_model_ctl(req: dict) -> dict` answers 'PDMV';
        # `stats_extra() -> dict` is merged into the 'PDHQ' payload (the
        # agent reports per-tenant SLO + memory there)
        self.on_drain = on_drain
        self.on_model_ctl = on_model_ctl
        self.stats_extra = stats_extra
        self.drain_info: dict = {}  # merged into the 'PDDR' drain report
        self._sock = _net.make_listener(host, port, backlog=64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self._drain_lock = threading.Lock()

    def start(self):
        self.engine.start()
        if self.llm_engine is not None:
            self.llm_engine.start()
        self._thread = _syncwatch.Thread(target=self._serve, daemon=True,
                                        name="predictor-serve")
        self._thread.start()
        return self

    def register_model(self, name: str, engine: ServingEngine):
        """Host an additional named model; its engine is started here and
        drained with the server's own."""
        engine.start()
        self.engines[name] = engine
        return engine

    def unregister_model(self, name: str, drain: bool = True):
        eng = self.engines.pop(name, None)
        if eng is not None:
            eng.stop(drain=drain)

    def _engine_for(self, model: Optional[str]) -> Optional[ServingEngine]:
        if model is None:
            return self.engine
        return self.engines.get(model)

    def _serve(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return  # drained/stopped before this thread first ran
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn = _net.secure_server(conn, "serving")
            except (_net.AuthError, OSError, ValueError):
                continue  # unauthenticated/broken peer: counted + dropped
            _syncwatch.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle_one(self, conn) -> bool:
        """One request/response exchange; False = close the connection."""
        # recv_head strips any 'PDDL' deadline prefix: expired work is
        # dropped HERE (DeadlineExpiredError -> _handle closes the conn)
        # instead of computed.
        head, _req_deadline = _net.recv_head(conn, 4, plane="serving")
        magic, = struct.unpack("<I", head)
        tctx = None
        model: Optional[str] = None
        read_deadline = None
        # OPTIONAL prefix frames ('PDTC' trace, 'PDMQ' model select) in
        # any order, then the verb magic. The first prefix arms the
        # read deadline: once a multi-frame exchange starts, the rest
        # must follow promptly.
        while magic in (_TRACE_MAGIC, _MODEL_MAGIC):
            if read_deadline is None:
                read_deadline = time.monotonic() + self._READ_TIMEOUT_S
            if magic == _TRACE_MAGIC:
                tctx = recv_trace_frame(conn, read_deadline)
            else:
                ln, = struct.unpack("<I", _recv_exact(conn, 4,
                                                      read_deadline))
                if ln > _MAX_NAME_LEN:
                    return False  # corrupt header: unrecoverable stream
                model = _recv_exact(conn, ln, read_deadline).decode(
                    "utf-8", "replace")
            magic, = struct.unpack("<I", _recv_exact(conn, 4,
                                                     read_deadline))
        if magic == _HEALTH_MAGIC:
            stats = self.stats()
            payload = json.dumps(stats, default=str).encode()
            conn.sendall(struct.pack("<IB", _RESP_MAGIC, STATUS_OK)
                         + struct.pack("<I", len(payload)) + payload)
            return True
        if magic == _DRAIN_MAGIC:
            report = self.drain()
            payload = json.dumps(report, default=str).encode()
            conn.sendall(struct.pack("<IB", _RESP_MAGIC, STATUS_OK)
                         + struct.pack("<I", len(payload)) + payload)
            return False  # drained: nothing more to serve
        if magic == _MODEL_CTL_MAGIC:
            return self._handle_model_ctl(conn)
        if magic == _STREAM_REQ_MAGIC:
            rspan = _trace.server_span("serving.stream", tctx)
            try:
                keep = self._handle_stream(conn, rspan)
            except BaseException as e:
                rspan.end(status=_trace.STATUS_ERROR,
                          error=f"{type(e).__name__}: {str(e)[:200]}")
                raise
            rspan.end()
            return keep
        # serving.request: the server-side root of this request's trace,
        # parented on the client's wire context; closes with the same
        # status the wire response carries (absence of 'PDTC' -> no-op)
        rspan = _trace.server_span("serving.request", tctx)
        try:
            keep = self._handle_request(conn, magic, rspan, model)
        except BaseException as e:
            rspan.end(status=_trace.STATUS_ERROR,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            raise
        rspan.end()  # idempotent: error paths already set their status
        return keep

    def _handle_model_ctl(self, conn) -> bool:
        read_deadline = time.monotonic() + self._READ_TIMEOUT_S
        ln, = struct.unpack("<I", _recv_exact(conn, 4, read_deadline))
        if ln > _MAX_NAME_LEN:
            return False
        try:
            req = json.loads(_recv_exact(conn, ln, read_deadline).decode())
        except ValueError:
            send_status_frame(conn, STATUS_ERROR, "bad model-ctl body")
            return False
        if self.on_model_ctl is None:
            send_status_frame(conn, STATUS_ERROR,
                              "model control not supported here")
            return True
        try:
            resp = self.on_model_ctl(req)
        except Exception as e:
            send_status_frame(conn, STATUS_ERROR,
                              f"{type(e).__name__}: {str(e)[:300]}")
            return True
        payload = json.dumps(resp, default=str).encode()
        conn.sendall(struct.pack("<IB", _RESP_MAGIC, STATUS_OK)
                     + struct.pack("<I", len(payload)) + payload)
        return True

    def _handle_request(self, conn, magic, rspan,
                        model: Optional[str] = None) -> bool:
        read_deadline = time.monotonic() + self._READ_TIMEOUT_S
        deadline_ms = None
        if magic == _REQ_DEADLINE_MAGIC:
            dl, = struct.unpack("<I", _recv_exact(conn, 4, read_deadline))
            deadline_ms = float(dl) if dl else None
        elif magic != _REQ_MAGIC:
            rspan.end(status=_trace.STATUS_ERROR, error="bad magic")
            return False  # protocol violation: drop the connection
        n, = struct.unpack("<I", _recv_exact(conn, 4, read_deadline))
        try:
            inputs = [_read_tensor(conn, read_deadline) for _ in range(n)]
        except ValueError as e:
            # header was bad: stream unrecoverable, report + close
            rspan.end(status=_trace.STATUS_ERROR, error=str(e)[:200])
            send_status_frame(conn, STATUS_ERROR, str(e))
            return False
        if self._draining:
            # tensors were consumed (stream stays framed) but no new work
            # is accepted: overloaded is the retry-elsewhere signal
            rspan.end(status=_trace.STATUS_REJECTED)
            send_status_frame(conn, STATUS_OVERLOADED, "replica draining")
            return True
        engine = self._engine_for(model)
        if engine is None:
            rspan.end(status=_trace.STATUS_ERROR, error="unknown model")
            send_status_frame(conn, STATUS_ERROR,
                              f"unknown model {model!r}")
            return True
        try:
            fut = engine.submit(inputs, deadline_ms=deadline_ms,
                                trace_ctx=rspan.ctx())
            outs = fut.result(timeout=self._RESULT_TIMEOUT_S)
        except (ServerOverloadedError, EngineStoppedError) as e:
            # a stopped/draining engine is backpressure, not failure:
            # the client should fail over to another replica
            rspan.end(status=_trace.STATUS_REJECTED)
            send_status_frame(conn, STATUS_OVERLOADED, str(e))
            return True
        except DeadlineExceededError as e:
            rspan.end(status=_trace.STATUS_DEADLINE)
            send_status_frame(conn, STATUS_DEADLINE, str(e))
            return True
        except Exception as e:  # surface model errors to the C app
            rspan.end(status=_trace.STATUS_ERROR,
                      error=f"{type(e).__name__}: {str(e)[:200]}")
            send_status_frame(conn, STATUS_ERROR, str(e))
            return True
        with _trace.server_span("serving.reply", rspan.ctx(),
                                attrs={"n_outputs": len(outs)}):
            conn.sendall(struct.pack("<IBI", _RESP_MAGIC, STATUS_OK,
                                     len(outs)))
            for o in outs:
                _write_tensor(conn, np.asarray(o))
        return True

    def _handle_stream(self, conn, rspan) -> bool:
        """'PDSQ' streaming generation. This handler thread is the SINGLE
        socket writer: it drains the LLMStream's token queue and pushes a
        'PDST' frame per token, then the terminal 'PDRS' — the scheduler
        thread never touches the connection."""
        read_deadline = time.monotonic() + self._READ_TIMEOUT_S
        max_new, dl, n = struct.unpack(
            "<III", _recv_exact(conn, 12, read_deadline))
        try:
            inputs = [_read_tensor(conn, read_deadline) for _ in range(n)]
        except ValueError as e:
            rspan.end(status=_trace.STATUS_ERROR, error=str(e)[:200])
            send_status_frame(conn, STATUS_ERROR, str(e))
            return False
        if self.llm_engine is None:
            send_status_frame(conn, STATUS_ERROR,
                              "no llm engine hosted here")
            return True
        if n != 1 or self._draining:
            if self._draining:
                rspan.end(status=_trace.STATUS_REJECTED)
                send_status_frame(conn, STATUS_OVERLOADED,
                                  "replica draining")
            else:
                send_status_frame(conn, STATUS_ERROR,
                                  f"stream request wants 1 prompt "
                                  f"tensor, got {n}")
            return True
        from ..serving import ServingError
        try:
            stream = self.llm_engine.submit(
                np.asarray(inputs[0]).reshape(-1),
                max_new_tokens=int(max_new) or None,
                deadline_ms=float(dl) if dl else None)
        except (ServerOverloadedError, EngineStoppedError) as e:
            rspan.end(status=_trace.STATUS_REJECTED)
            send_status_frame(conn, STATUS_OVERLOADED, str(e))
            return True
        except ServingError as e:
            rspan.end(status=_trace.STATUS_ERROR, error=str(e)[:200])
            send_status_frame(conn, STATUS_ERROR, str(e))
            return True
        try:
            for idx, tok in enumerate(stream.iter(
                    timeout=self._RESULT_TIMEOUT_S)):
                conn.sendall(struct.pack("<IIi", _STREAM_MAGIC, idx, tok))
        except Exception:
            # consumer gone or queue starved: the sequence keeps running
            # server-side until its own budget/deadline evicts it
            rspan.end(status=_trace.STATUS_ERROR, error="stream broken")
            raise
        status, tokens = stream.result(timeout=1.0)
        if status == "done":
            conn.sendall(struct.pack("<IBI", _RESP_MAGIC, STATUS_OK, 1))
            _write_tensor(conn, np.asarray(tokens, np.int32))
        elif status == "deadline":
            rspan.end(status=_trace.STATUS_DEADLINE)
            send_status_frame(conn, STATUS_DEADLINE,
                              "generation deadline exceeded")
        elif status == "stopped":
            rspan.end(status=_trace.STATUS_REJECTED)
            send_status_frame(conn, STATUS_OVERLOADED, "engine stopped")
        else:
            rspan.end(status=_trace.STATUS_ERROR,
                      error=(stream.error or status)[:200])
            send_status_frame(conn, STATUS_ERROR, stream.error or status)
        return True

    def _handle(self, conn):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while self._handle_one(conn):
                pass
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            conn.close()

    def stats(self):
        """Engine health snapshot (what the 'PDHQ' wire probe returns):
        queue/bucket/deadline counters plus `warm_start_ms` and the
        `compile_cache` hit/miss stats, so a fleet dashboard can tell a
        replica that warm-started from the persistent executable cache
        from one that paid its own compiles. Hosted models appear under
        `models`; a `stats_extra()` hook merges on top (fleet agents
        report per-tenant SLO + memory there)."""
        stats = self.engine.stats()
        stats["draining"] = self._draining
        if self.llm_engine is not None:
            stats["llm"] = self.llm_engine.stats()
        if self.engines:
            stats["models"] = {name: eng.stats()
                               for name, eng in self.engines.items()}
        if self.stats_extra is not None:
            try:
                stats.update(self.stats_extra())
            except Exception:
                pass  # a broken hook must not break the health probe
        return stats

    def _close_listener(self):
        # shutdown() BEFORE close(): close() alone only drops this
        # process's fd — a parked accept() or a connecting peer can keep
        # the port half-alive. shutdown() tears the socket down
        # immediately so the port is observably closed (PR-3 regression).
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)

    def drain(self) -> dict:
        """Graceful drain ('PDDR'): every ACCEPTED request completes or is
        rejected with the overloaded status — never silently dropped.
        Ordering: (1) mark draining so requests still arriving on live
        connections get STATUS_OVERLOADED, (2) close the listening port
        (no new connections), (3) `on_drain()` (the fleet agent
        deregisters its lease), (4) every engine finishes its queued work
        (`stop(drain=True)`). Idempotent; returns the drain report."""
        with self._drain_lock:
            already = self._draining
            self._draining = True
        if already:
            return {"drained": True, "already": True}
        self._close_listener()
        if self.on_drain is not None:
            try:
                self.on_drain()
            except Exception:
                pass  # the drain itself must still complete
        report = {"drained": True, "completed": {}, "port": self.port,
                  **self.drain_info}
        for name, eng in [("", self.engine), *self.engines.items()]:
            eng.stop(drain=True)
            counts = eng.stats().get("counters", {})
            report["completed"][name or "default"] = \
                counts.get("completed", 0)
        if self.llm_engine is not None:
            self.llm_engine.stop(drain=True)
            report["completed"]["llm"] = self.llm_engine.stats()[
                "counters"].get("completed", 0)
        return report

    def stop(self, drain: bool = True):
        if drain:
            self.drain()
            return
        self._draining = True
        self._close_listener()
        self.engine.stop(drain=False)
        for eng in self.engines.values():
            eng.stop(drain=False)
        if self.llm_engine is not None:
            self.llm_engine.stop(drain=False)


class ReplicaConnectError(ConnectionError):
    """No replica accepted a connection within the retry budget."""


class PredictorClient:
    """Python-side client of the wire protocol (the C client in
    csrc/predict_capi.cpp is the production ABI; this one drives tests,
    tooling and the fleet router — including the health probe).

    Hardened the same way the PS RPC plane is (FLAGS_ps_rpc_* lineage):
    connects are BOUNDED — `FLAGS_serving_client_max_retries` attempts
    with exponential backoff and full jitter, each capped at
    `FLAGS_serving_client_connect_timeout_s` — and every call takes an
    optional deadline that bounds the wire wait, so a wedged replica
    surfaces as TimeoutError instead of a hang.

    Construct with a single `(host, port)` (back-compat) or
    `replicas=[(h, p), ...]`; with several replicas, transport errors
    transparently fail over to the next one (`failover=False` for
    at-most-one-attempt callers like the fleet router, which keeps its
    own exactly-once ledger)."""

    def __init__(self, host=None, port=None, timeout: float = 60.0,
                 replicas=None, failover: Optional[bool] = None,
                 max_retries: Optional[int] = None,
                 backoff_ms: Optional[float] = None,
                 connect_timeout: Optional[float] = None):
        if replicas is None:
            if host is None or port is None:
                raise ValueError("need (host, port) or replicas=[...]")
            replicas = [(host, int(port))]
        self.replicas = [(h, int(p)) for h, p in replicas]
        self.timeout = timeout
        self.failover = (len(self.replicas) > 1) if failover is None \
            else failover
        self._max_retries = int(_flags.flag("serving_client_max_retries")
                                if max_retries is None else max_retries)
        self._backoff_ms = float(_flags.flag("serving_client_backoff_ms")
                                 if backoff_ms is None else backoff_ms)
        self._connect_timeout = float(
            _flags.flag("serving_client_connect_timeout_s")
            if connect_timeout is None else connect_timeout)
        self._idx = 0  # replica the live connection points at
        # the serving plane's substrate channel: the resolver serves the
        # replica list rotated to start at the current index, so failover
        # (`self._idx += 1`) naturally re-resolves to the next replica
        self._chan = _net.RpcChannel(
            "serving", resolver=self._rotation,
            connect_timeout=self._connect_timeout,
            on_connect=self._note_connected)
        self._connect()

    # wire status -> terminal span status for the client.send root span
    _SPAN_STATUS = {STATUS_OK: _trace.STATUS_OK,
                    STATUS_ERROR: _trace.STATUS_ERROR,
                    STATUS_OVERLOADED: _trace.STATUS_REJECTED,
                    STATUS_DEADLINE: _trace.STATUS_DEADLINE}

    @property
    def endpoint(self):
        """(host, port) the live connection points at."""
        return self.replicas[self._idx % len(self.replicas)]

    def _rotation(self):
        n = len(self.replicas)
        return [self.replicas[(self._idx + k) % n] for k in range(n)]

    def _note_connected(self, chan):
        # channel landed somewhere in the rotation: remember which
        # replica, and arm the per-call read timeout on the live socket
        self._idx = self.replicas.index(chan.endpoint)
        chan.sock.settimeout(self.timeout)

    def _connect(self, deadline: Optional[float] = None):
        """Bounded connect: up to max_retries+1 rounds over the replica
        list (one RpcChannel.connect sweep per round), exponential
        backoff with FULL jitter between rounds (decorr against
        thundering-herd reconnects), the whole dance optionally bounded
        by an absolute `deadline`."""
        self._disconnect()
        last: Optional[Exception] = None
        for attempt in range(self._max_retries + 1):
            try:
                self._chan.connect(deadline)
                return
            except _net.ConnectDeadlineError:
                raise
            except OSError as e:
                last = e
            if attempt < self._max_retries:
                # full jitter: sleep U(0, base * 2^attempt)
                delay = random.random() * (self._backoff_ms / 1000.0
                                           ) * (2 ** attempt)
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                time.sleep(delay)
        raise ReplicaConnectError(
            f"no replica reachable after {self._max_retries + 1} "
            f"rounds over {self.replicas}") from last

    def _disconnect(self):
        self._chan.drop()

    def _ensure(self, deadline: Optional[float] = None):
        if not self._chan.connected:
            self._connect(deadline)
        return self._chan.sock

    def run(self, arrays, deadline_ms: Optional[float] = None,
            model: Optional[str] = None):
        """Returns (status, payload): payload is the output list on
        STATUS_OK, else the server's utf-8 message. `deadline_ms` rides
        the wire ('PDRD') AND bounds the local wait; `model` sends the
        'PDMQ' prefix to pick a hosted model on a multi-model replica.

        With several replicas (and `failover` on), a transport error
        moves to the next replica and retries the WHOLE request within
        the original deadline. That is at-least-once: a reply lost in
        flight may mean the work ran twice — callers needing
        exactly-once (the fleet router) set failover=False and keep a
        sequence ledger.

        Under `FLAGS_trace` each call mints a new trace: a `client.send`
        root span whose context rides a 'PDTC' prefix frame, so the
        server (and engine) spans land in the SAME trace. Tracing off =
        byte-identical frames to the pre-PDTC protocol."""
        deadline = (time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms is not None else None)
        attempts = len(self.replicas) if self.failover else 1
        last: Optional[Exception] = None
        for attempt in range(attempts):
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("client deadline exceeded") from last
            try:
                return self._run_once(arrays, deadline_ms, deadline, model)
            except (ConnectionError, TimeoutError, OSError,
                    struct.error) as e:
                last = e
                self._disconnect()
                self._idx += 1  # next attempt starts at the next replica
        raise last  # type: ignore[misc]

    def _run_once(self, arrays, deadline_ms, deadline, model):
        sock = self._ensure(deadline)
        with _trace.span("client.send",
                         attrs={"n_tensors": len(arrays)}) as sp:
            if deadline is not None and _net.deadline_wire_enabled():
                _net.send_deadline(sock, deadline)
            if sp.trace_id is not None:
                send_trace_frame(sock, sp.ctx())
            if model is not None:
                name = model.encode()
                sock.sendall(struct.pack("<II", _MODEL_MAGIC, len(name))
                             + name)
            if deadline_ms is not None:
                hdr = struct.pack("<III", _REQ_DEADLINE_MAGIC,
                                  int(deadline_ms), len(arrays))
            else:
                hdr = struct.pack("<II", _REQ_MAGIC, len(arrays))
            hdr = self._chan.check_send_faults(hdr)
            sock.sendall(hdr)
            for a in arrays:
                _write_tensor(sock, np.asarray(a))
            self._chan.check_recv_faults()
            magic, status = struct.unpack(
                "<IB", _recv_exact(sock, 5, deadline))
            if magic != _RESP_MAGIC:
                raise ConnectionError(f"bad response magic {magic:#x}")
            if status != STATUS_OK:
                ln, = struct.unpack("<I", _recv_exact(sock, 4, deadline))
                msg = _recv_exact(sock, ln, deadline).decode()
                sp.end(status=self._SPAN_STATUS.get(
                    status, _trace.STATUS_ERROR))
                return status, msg
            n, = struct.unpack("<I", _recv_exact(sock, 4, deadline))
            return status, [_read_tensor(sock, deadline)
                            for _ in range(n)]

    def generate(self, prompt_ids, max_new_tokens: int = 32,
                 deadline_ms: Optional[float] = None, on_token=None):
        """Streaming LLM generation over 'PDSQ'. Returns (status,
        payload): the full token list on STATUS_OK, else the server's
        message. `on_token(index, token)` fires per 'PDST' frame as it
        arrives, which is the streaming part — by the time this returns,
        the generation is over.

        No failover: a stream is stateful on its replica, so a transport
        error mid-generation surfaces to the caller instead of silently
        re-running the prompt elsewhere (tokens already delivered cannot
        be un-streamed)."""
        deadline = (time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms is not None else None)
        sock = self._ensure(deadline)
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if deadline is not None and _net.deadline_wire_enabled():
            _net.send_deadline(sock, deadline)
        hdr = self._chan.check_send_faults(
            struct.pack("<IIII", _STREAM_REQ_MAGIC, int(max_new_tokens),
                        int(deadline_ms or 0), 1))
        sock.sendall(hdr)
        _write_tensor(sock, prompt)
        self._chan.check_recv_faults()
        tokens = []
        while True:
            magic, = struct.unpack("<I", _recv_exact(sock, 4, deadline))
            if magic == _STREAM_MAGIC:
                idx, tok = struct.unpack(
                    "<Ii", _recv_exact(sock, 8, deadline))
                tokens.append(tok)
                if on_token is not None:
                    on_token(idx, tok)
                continue
            if magic != _RESP_MAGIC:
                raise ConnectionError(f"bad stream magic {magic:#x}")
            status, = struct.unpack("<B", _recv_exact(sock, 1, deadline))
            if status != STATUS_OK:
                ln, = struct.unpack("<I", _recv_exact(sock, 4, deadline))
                return status, _recv_exact(sock, ln, deadline).decode()
            n, = struct.unpack("<I", _recv_exact(sock, 4, deadline))
            final = [_read_tensor(sock, deadline) for _ in range(n)]
            if final:
                tokens = [int(t) for t in np.asarray(final[0]).reshape(-1)]
            return status, tokens

    def _json_exchange(self, magic: int, body: bytes = b"",
                       deadline_ms: Optional[float] = None) -> dict:
        deadline = (time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms is not None else None)
        sock = self._ensure(deadline)
        if deadline is not None and _net.deadline_wire_enabled():
            _net.send_deadline(sock, deadline)
        if body:
            hdr = struct.pack("<II", magic, len(body)) + body
        else:
            hdr = struct.pack("<I", magic)
        sock.sendall(self._chan.check_send_faults(hdr))
        self._chan.check_recv_faults()
        rmagic, status = struct.unpack("<IB", _recv_exact(sock, 5,
                                                          deadline))
        if rmagic != _RESP_MAGIC:
            raise ConnectionError(f"bad response magic {rmagic:#x}")
        ln, = struct.unpack("<I", _recv_exact(sock, 4, deadline))
        payload = _recv_exact(sock, ln, deadline).decode()
        if status != STATUS_OK:
            raise ConnectionError(f"status {status}: {payload}")
        return json.loads(payload)

    def health(self, deadline_ms: Optional[float] = None) -> dict:
        return self._json_exchange(_HEALTH_MAGIC, deadline_ms=deadline_ms)

    def drain(self, deadline_ms: Optional[float] = None) -> dict:
        """Graceful drain ('PDDR'); returns the replica's drain report.
        The server closes the connection afterwards."""
        report = self._json_exchange(_DRAIN_MAGIC, deadline_ms=deadline_ms)
        self._disconnect()
        return report

    def model_ctl(self, op: str, model: str,
                  deadline_ms: Optional[float] = None) -> dict:
        """'PDMV' model-version control: op is `reload` or `rollback`."""
        body = json.dumps({"op": op, "model": model}).encode()
        return self._json_exchange(_MODEL_CTL_MAGIC, body,
                                   deadline_ms=deadline_ms)

    def close(self):
        self._disconnect()
