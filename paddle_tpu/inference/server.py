"""Predictor service — the server side of the C inference API.

Reference parity: the deployment role of `inference/capi_exp/` +
`goapi/`: C/Go apps run inference against a stable ABI. Here the ABI is a
binary tensor protocol (see csrc/predict_capi.cpp) served by the process
that owns the TPU runtime; each connection gets a handler thread and runs
the shared Predictor (Predictor.clone()-style multi-threaded serving,
`analysis_predictor.cc` Clone).
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

import numpy as np

_REQ_MAGIC = 0x50445251
_RESP_MAGIC = 0x50445253
_DTYPES = {0: np.float32, 1: np.int32, 2: np.int64}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                np.dtype(np.int64): 2}
_MAX_NDIM = 8
_MAX_TENSOR_BYTES = 1 << 32  # sanity cap against corrupt headers

from ..utils.net import recv_exact as _recv_exact  # noqa: E402


def _read_tensor(conn) -> np.ndarray:
    dt, ndim = struct.unpack("<II", _recv_exact(conn, 8))
    if dt not in _DTYPES or ndim > _MAX_NDIM:
        raise ValueError(f"bad tensor header dtype={dt} ndim={ndim}")
    dims = struct.unpack(f"<{ndim}q", _recv_exact(conn, 8 * ndim))
    dtype = _DTYPES[dt]
    if any(d < 0 for d in dims):
        raise ValueError(f"bad tensor dims {dims}")
    count = int(np.prod(dims)) if dims else 1
    nbytes = count * dtype().itemsize
    if nbytes > _MAX_TENSOR_BYTES:
        raise ValueError(f"tensor payload {nbytes} bytes exceeds cap")
    payload = _recv_exact(conn, nbytes)
    return np.frombuffer(payload, dtype).reshape(dims).copy()


def _write_tensor(conn, arr: np.ndarray):
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _DTYPE_CODES:
        arr = arr.astype(np.float32)
    conn.sendall(struct.pack("<II", _DTYPE_CODES[arr.dtype], arr.ndim)
                 + struct.pack(f"<{arr.ndim}q", *arr.shape)
                 + arr.tobytes())


class PredictorServer:
    """Serve a Predictor (or any callable of numpy arrays) over the C-API
    wire protocol."""

    def __init__(self, predictor, host="127.0.0.1", port=0):
        self.predictor = predictor
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # predictor state is shared

    def start(self):
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _run(self, inputs):
        from . import Predictor
        if isinstance(self.predictor, Predictor):
            with self._lock:
                names = self.predictor.get_input_names()
                if len(inputs) != len(names):
                    raise ValueError(
                        f"model expects {len(names)} inputs, got {len(inputs)}")
                for name, arr in zip(names, inputs):
                    self.predictor.get_input_handle(name).copy_from_cpu(arr)
                self.predictor.run()
                return [self.predictor.get_output_handle(n).copy_to_cpu()
                        for n in self.predictor.get_output_names()]
        outs = self.predictor(*inputs)
        return list(outs) if isinstance(outs, (list, tuple)) else [outs]

    def _handle(self, conn):
        try:
            while True:
                magic, n = struct.unpack("<II", _recv_exact(conn, 8))
                if magic != _REQ_MAGIC:
                    return  # protocol violation: drop the connection
                try:
                    inputs = [_read_tensor(conn) for _ in range(n)]
                except ValueError as e:
                    # header was bad: stream unrecoverable, report + close
                    msg = str(e).encode()
                    conn.sendall(struct.pack("<IB", _RESP_MAGIC, 1)
                                 + struct.pack("<I", len(msg)) + msg)
                    return
                try:
                    outs = self._run(inputs)
                except Exception as e:  # surface model errors to the C app
                    msg = str(e).encode()
                    conn.sendall(struct.pack("<IB", _RESP_MAGIC, 1)
                                 + struct.pack("<I", len(msg)) + msg)
                    continue
                conn.sendall(struct.pack("<IBI", _RESP_MAGIC, 0, len(outs)))
                for o in outs:
                    _write_tensor(conn, np.asarray(o))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)
