"""paddle.inference parity: Config + Predictor.

Reference parity: `paddle/fluid/inference/api/analysis_predictor.cc`
(AnalysisPredictor: load → optimize program → ZeroCopyRun) and
`paddle_analysis_config.h`. TPU-native: the "optimized program" IS the XLA
executable — jit.save's exported StableHLO artifact (or a live Layer traced
on the fly); ir-pass fusion work is done by XLA. ZeroCopyTensor maps to
device arrays handed across with no host copy.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class Config:
    """AnalysisConfig parity (device/precision knobs that matter on TPU)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu"
        self._precision = "float32"
        self._memory_optim = True
        self._quant = False
        self._options = {}  # recorded knobs: TPU-mapped or explicit N/A

    # paddle API spellings
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # gpu requests route to the accelerator

    def enable_tpu(self):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def set_cpu_math_library_num_threads(self, n):
        # XLA:CPU threading is runtime-owned; recorded for summary()
        self._options["cpu_math_threads"] = int(n)

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def enable_mkldnn(self):
        # oneDNN is an x86 backend concern: N/A on TPU, XLA fuses instead
        self._options["mkldnn"] = "n/a-on-tpu (XLA fusion)"

    def enable_tensorrt_engine(self, workspace_size=1 << 30, max_batch_size=1,
                               min_subgraph_size=3, precision_mode="float32",
                               use_static=False, use_calib_mode=False):
        # TRT subgraphs ⇒ XLA whole-graph; the precision hint IS honored
        self._precision = precision_mode if isinstance(precision_mode, str) else "float16"
        self._options["trt"] = f"mapped-to-XLA (precision={self._precision})"
        if self._precision == "int8":
            # the standard paddle int8 spelling routes through the same
            # quant verification as enable_quant()
            self.enable_quant()

    def enable_quant(self, bits=8):
        """Serve a weight-only int8 artifact (mkldnn_quantizer/TRT-int8
        role): the artifact must have been exported with
        jit.save(..., precision='int8') — quantization is an export-time
        transform here, the predictor verifies and runs it."""
        if bits != 8:
            raise ValueError("only int8 weight-only quantization is supported")
        self._options["quant"] = "int8-weight-only"
        self._quant = True

    def switch_use_feed_fetch_ops(self, flag):
        self._options["feed_fetch_ops"] = bool(flag)  # zero-copy either way

    def switch_ir_optim(self, flag=True):
        # XLA optimization always runs; recorded so summary() shows intent
        self._options["ir_optim"] = bool(flag)

    def precision(self):
        return self._precision

    def summary(self) -> str:
        """Effective config incl. which knobs are TPU-mapped vs N/A
        (AnalysisConfig::Summary role)."""
        lines = [f"device: {self._device}", f"precision: {self._precision}",
                 f"memory_optim: {self._memory_optim}"]
        lines += [f"{k}: {v}" for k, v in sorted(self._options.items())]
        return "\n".join(lines)


class PredictorTensor:
    """ZeroCopyTensor parity — a named input/output slot."""

    def __init__(self, predictor, name, is_input):
        self._pred = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        a = jnp.asarray(np.asarray(arr))
        # cast once at feed time, not in every run() (predictor hot loop)
        if self._pred._bf16 and jnp.issubdtype(a.dtype, jnp.floating):
            a = a.astype(jnp.bfloat16)
        self._pred._feeds[self.name] = a

    def reshape(self, shape):
        pass

    def copy_to_cpu(self):
        a = np.asarray(self._pred._results[self.name])
        if a.dtype == np.dtype("bfloat16"):
            a = a.astype(np.float32)  # bf16 artifacts read back as fp32
        return a

    def device_value(self):
        """Zero-copy device array of this output (no host transfer, no
        dtype view) — the TPU-native ZeroCopyTensor read path."""
        return self._pred._results[self.name]

    def share_external_data(self, tensor):
        self._pred._feeds[self.name] = tensor._value if isinstance(tensor, Tensor) else tensor


class Predictor:
    def __init__(self, config_or_layer, input_spec=None):
        self._feeds = {}
        self._results = {}
        self._fn = None
        self._input_names = []
        self._output_names = []
        if isinstance(config_or_layer, Config):
            cfg = config_or_layer
            import os
            from ..core.enforce import NotFoundError
            from ..jit.save_load import load as jload
            path = cfg.model_path
            if path.endswith(".pdmodel"):
                path = path[:-len(".pdmodel")]
            if not os.path.exists(path + ".pdmodel"):
                raise NotFoundError(
                    f"Cannot open model file {path}.pdmodel\n"
                    "  [Hint] save the model with paddle_tpu.jit.save first.")
            self._translated = jload(path)
            meta = self._translated._meta
            is_int8 = (meta.get("precision") == "int8"
                       or bool(meta.get("quantized")))
            if getattr(cfg, "_quant", False) and not is_int8:
                from ..core.enforce import InvalidArgumentError
                raise InvalidArgumentError(
                    "Config.enable_quant() requires an int8 artifact\n"
                    "  [Hint] re-export with jit.save(..., precision='int8')")
            specs = self._translated._meta["input_specs"]
            self._input_names = [f"input_{i}" for i in range(len(specs))]
            # the artifact's exported signature decides the feed dtype: a
            # bf16-saved model needs bf16 feeds even if the Config is silent,
            # and a fp32-saved model must NOT have its feeds cast no matter
            # what precision the Config asks for (the StableHLO signature is
            # fixed at save time; precision is an export-time choice here)
            self._bf16 = any(s.get("dtype") == "bfloat16" for s in specs)
        else:
            layer = config_or_layer
            layer.eval()
            self._translated = None
            self._layer = layer
            self._input_spec = input_spec
            self._input_names = [f"input_{i}" for i in range(len(input_spec or [1]))]
            self._bf16 = False
        self._output_names = ["output_0"]

    # --- paddle.inference API ---
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        return PredictorTensor(self, name, True)

    def get_output_handle(self, name):
        return PredictorTensor(self, name, False)

    def run(self, inputs=None):
        if inputs is not None:
            arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(np.asarray(a))
                    for a in inputs]
        else:
            arrs = [self._feeds[n] for n in self._input_names]
        if self._bf16:
            arrs = [a.astype(jnp.bfloat16)
                    if jnp.issubdtype(a.dtype, jnp.floating)
                    and a.dtype != jnp.bfloat16 else a
                    for a in arrs]
        if self._translated is not None:
            out = self._translated(*arrs)
        else:
            if self._fn is None:
                from ..jit.to_static import to_static
                self._fn = to_static(self._layer.forward)
            from ..core.autograd import no_grad
            with no_grad():
                out = self._fn(*[Tensor(a) for a in arrs])
        outs = out if isinstance(out, (list, tuple)) else [out]
        # keep raw (possibly bf16) device arrays: the fp32 view happens
        # lazily in copy_to_cpu, so the hot loop issues exactly ONE device
        # dispatch per run() (matters on high-latency dispatch paths)
        outs = [o._value for o in outs]
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        self._results = dict(zip(self._output_names, outs))
        if inputs is not None:
            return [Tensor(o.astype(jnp.float32)
                           if jnp.issubdtype(o.dtype, jnp.bfloat16) else o)
                    for o in outs]
        return None

    # ZeroCopyRun parity
    zero_copy_run = run

    def serving_buckets(self, ladder=None):
        """Shape-bucket declarations for the serving engine, derived from
        the artifact's exported input specs: [(item_shapes, dtypes,
        batch_sizes)]. A saved artifact has a FIXED StableHLO signature,
        so its only legal batch size is the exported one (requests pad up
        to it); a live-Layer predictor retraces freely, so it gets the
        engine's batch ladder. Dynamic (-1) dims defer to bucket learning."""
        if self._translated is not None:
            specs = self._translated._meta["input_specs"]
            fixed = True
        elif getattr(self, "_input_spec", None):
            specs = [{"shape": list(s.shape), "dtype": s.dtype}
                     for s in self._input_spec]
            fixed = False
        else:
            return []
        shapes = [tuple(int(d) for d in s["shape"]) for s in specs]
        if any(len(s) < 1 or any(d < 0 for d in s) for s in shapes):
            return []
        # the wire carries f32/i32/i64; run_batch casts floats for bf16
        # artifacts, so float-family specs bucket as float32 on the host
        dtypes = ["float32" if "float" in np.dtype(s["dtype"]).name
                  else np.dtype(s["dtype"]).name for s in specs]
        batches = {s[0] for s in shapes}
        if len(batches) != 1:
            return []
        batch = batches.pop()
        sizes = [batch] if fixed else sorted(
            {b for b in (ladder or [batch]) } | {batch})
        return [([s[1:] for s in shapes], dtypes, sizes)]

    def run_batch(self, arrays):
        """Batched functional entry for the serving plane: a list of
        numpy/jax arrays (leading dim = batch) in, a list of HOST numpy
        arrays out. Unlike run(), it touches no handle state (_feeds/
        _results), so engine workers can drive it without the per-request
        lock the handle protocol needs; bf16 artifacts read back as fp32
        exactly like copy_to_cpu."""
        if len(arrays) != len(self._input_names):
            raise ValueError(f"model expects {len(self._input_names)} "
                             f"inputs, got {len(arrays)}")
        arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in arrays]
        if self._bf16:
            arrs = [a.astype(jnp.bfloat16)
                    if jnp.issubdtype(a.dtype, jnp.floating)
                    and a.dtype != jnp.bfloat16 else a
                    for a in arrs]
        if self._translated is not None:
            out = self._translated(*arrs)
        else:
            if self._fn is None:
                from ..jit.to_static import to_static
                self._fn = to_static(self._layer.forward)
            from ..core.autograd import no_grad
            with no_grad():
                out = self._fn(*[Tensor(a) for a in arrs])
        outs = out if isinstance(out, (list, tuple)) else [out]
        res = []
        for o in outs:
            a = np.asarray(o._value if isinstance(o, Tensor) else o)
            if a.dtype == np.dtype("bfloat16"):
                a = a.astype(np.float32)
            res.append(a)
        return res


def create_predictor(config):
    return Predictor(config)


__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]
