"""paddle.sparse parity: COO/CSR tensors + elementwise/unary/spmm ops.

Reference parity: `phi/core/sparse_coo_tensor.h` / `sparse_csr_tensor.h`,
kernels under `paddle/phi/kernels/sparse/` (elementwise, matmul, unary,
mask), python surface `python/paddle/sparse` (later tree; the 2022
snapshot ships `paddle.incubate.sparse` with the same ops).

TPU-first: XLA has no native sparse kernels, so values ride as dense
[nnz] / [nnz, ...] arrays with host-resident index metadata, ops lower to
gather/segment-scatter (the reference's own GPU fallback strategy), and
every op routes values through the autograd tape — gradients flow to the
values (and the dense operand of spmm) like any dense op.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._dispatch import ensure_tensor, run_op

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "to_dense", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "relu", "abs", "sin", "tanh",
    "sqrt", "square", "pow", "neg", "cast", "coalesce", "is_same_shape",
    "transpose",
]


class SparseCooTensor:
    """COO: `indices` [sparse_dims, nnz] (host int64) + `values` Tensor."""

    def __init__(self, indices, values, shape):
        ind = indices.numpy() if isinstance(indices, Tensor) else indices
        self.indices = np.asarray(ind, np.int64)
        self.values = values if isinstance(values, Tensor) \
            else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    # -- introspection --
    def nnz(self):
        return self.values.shape[0]

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    @property
    def dtype(self):
        return self.values.dtype

    def numpy(self):
        return np.asarray(self.to_dense().numpy())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # -- conversion --
    def to_dense(self):
        idx = tuple(self.indices[i] for i in range(self.indices.shape[0]))
        shape = tuple(self.shape)
        return run_op(
            lambda v: jnp.zeros(shape, v.dtype).at[idx].add(v),
            [self.values], "coo_to_dense")

    def to_sparse_csr(self):
        if self.indices.shape[0] != 2:
            raise ValueError("to_sparse_csr requires a 2D COO tensor")
        coo = self.coalesce()
        rows, cols = coo.indices
        crows = np.zeros(coo.shape[0] + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(crows, cols, coo.values, coo.shape)

    def coalesce(self):
        """Sort indices lexicographically and sum duplicates."""
        sdims = self.indices.shape[0]
        dims = tuple(self.shape[:sdims])
        flat = np.ravel_multi_index(tuple(self.indices), dims)
        uniq, inv = np.unique(flat, return_inverse=True)
        new_idx = np.stack(np.unravel_index(uniq, dims))
        inv_j = jnp.asarray(inv)
        n_out = len(uniq)
        vals = run_op(
            lambda v: jnp.zeros((n_out,) + v.shape[1:], v.dtype)
            .at[inv_j].add(v), [self.values], "coo_coalesce")
        return SparseCooTensor(new_idx, vals, self.shape)

    # -- operators --
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def T(self):
        return transpose(self, list(range(len(self.shape)))[::-1])


class SparseCsrTensor:
    """CSR: `crows` [rows+1], `cols` [nnz] (host int64) + `values` Tensor."""

    def __init__(self, crows, cols, values, shape):
        self.crows = np.asarray(
            crows.numpy() if isinstance(crows, Tensor) else crows, np.int64)
        self.cols = np.asarray(
            cols.numpy() if isinstance(cols, Tensor) else cols, np.int64)
        self.values = values if isinstance(values, Tensor) \
            else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def nnz(self):
        return self.values.shape[0]

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    @property
    def dtype(self):
        return self.values.dtype

    def _rows(self):
        return np.repeat(np.arange(len(self.crows) - 1, dtype=np.int64),
                         np.diff(self.crows))

    def to_sparse_coo(self, sparse_dim=2):
        return SparseCooTensor(np.stack([self._rows(), self.cols]),
                               self.values, self.shape)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def numpy(self):
        return np.asarray(self.to_dense().numpy())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:  # infer dims from the index extents (reference API)
        ind = np.asarray(
            indices.numpy() if isinstance(indices, Tensor) else indices,
            np.int64)
        shape = list(ind.max(axis=1) + 1)
    t = SparseCooTensor(indices, values, shape)
    if dtype is not None:
        t = cast(t, value_dtype=dtype)
    t.values.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    t = SparseCsrTensor(crows, cols, values, shape)
    if dtype is not None:
        t = SparseCsrTensor(t.crows, t.cols, cast_values(t.values, dtype),
                            t.shape)
    t.values.stop_gradient = stop_gradient
    return t


def to_dense(x):
    return x.to_dense()


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _as_coo(x):
    return x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x


def _same_pattern(a, b):
    return a.indices.shape == b.indices.shape \
        and np.array_equal(a.indices, b.indices)


def _maybe_coalesce(x):
    sdims = x.indices.shape[0]
    flat = np.ravel_multi_index(tuple(x.indices), tuple(x.shape[:sdims]))
    return x.coalesce() if len(np.unique(flat)) < len(flat) else x


def _ewise(a, b, fn, name, require_same_pattern=False):
    """Sparse(+)sparse elementwise; result sparsity = union of patterns."""
    was_csr = isinstance(a, SparseCsrTensor)
    a, b = _as_coo(a), _as_coo(b)
    if list(a.shape) != list(b.shape):
        raise ValueError(f"sparse {name}: shape mismatch {a.shape} vs "
                         f"{b.shape}")
    # duplicate indices would be dropped by the union scatter (and have
    # ill-defined semantics for multiply/divide): coalesce first
    a, b = _maybe_coalesce(a), _maybe_coalesce(b)
    if require_same_pattern and not _same_pattern(a, b):
        raise ValueError(
            f"sparse.{name} requires operands with identical sparsity "
            "patterns (positions present in one but not the other would "
            "compute x/0 -> inf); densify or align patterns first")
    if _same_pattern(a, b):
        vals = run_op(fn, [a.values, b.values], f"sparse_{name}")
        out = SparseCooTensor(a.indices, vals, a.shape)
        return out.to_sparse_csr() if was_csr else out
    # union of patterns: scatter both into the union index set
    sdims = a.indices.shape[0]
    dims = tuple(a.shape[:sdims])
    fa = np.ravel_multi_index(tuple(a.indices), dims)
    fb = np.ravel_multi_index(tuple(b.indices), dims)
    uniq = np.union1d(fa, fb)
    pa = jnp.asarray(np.searchsorted(uniq, fa))
    pb = jnp.asarray(np.searchsorted(uniq, fb))
    n = len(uniq)

    def f(va, vb):
        ua = jnp.zeros((n,) + va.shape[1:], va.dtype).at[pa].set(va)
        ub = jnp.zeros((n,) + vb.shape[1:], vb.dtype).at[pb].set(vb)
        return fn(ua, ub)

    vals = run_op(f, [a.values, b.values], f"sparse_{name}")
    out = SparseCooTensor(np.stack(np.unravel_index(uniq, dims)), vals,
                          a.shape)
    return out.to_sparse_csr() if was_csr else out


def add(a, b):
    return _ewise(a, b, lambda x, y: x + y, "add")


def subtract(a, b):
    return _ewise(a, b, lambda x, y: x - y, "subtract")


def multiply(a, b):
    return _ewise(a, b, lambda x, y: x * y, "multiply")


def divide(a, b):
    # union-pattern semantics are only sound for add/sub/mul: a position
    # present in `a` but missing in `b` would divide by the implicit zero
    # and silently produce inf/nan — refuse instead (ADVICE r4); the check
    # rides inside _ewise where the operands are already coalesced
    return _ewise(a, b, lambda x, y: x / y, "divide",
                  require_same_pattern=True)


def matmul(a, dense):
    """Sparse [M, K] @ dense [K, N] -> dense Tensor [M, N] (spmm).

    Reference: `paddle/phi/kernels/sparse/` matmul (cusparse SpMM role).
    Lowered to gather + segment scatter-add; differentiable w.r.t. BOTH
    the sparse values and the dense operand.
    """
    a = _as_coo(a)
    dense = ensure_tensor(dense)
    if len(a.shape) != 2 or a.indices.shape[0] != 2:
        raise ValueError("sparse.matmul supports 2D sparse @ 2D dense")
    rows = jnp.asarray(a.indices[0])
    cols = jnp.asarray(a.indices[1])
    M = a.shape[0]

    def f(vals, d):
        contrib = vals[:, None] * d[cols]            # [nnz, N]
        return jnp.zeros((M, d.shape[1]), contrib.dtype).at[rows].add(contrib)

    return run_op(f, [a.values, dense], "sparse_matmul")


def masked_matmul(x, y, mask):
    """dense @ dense evaluated ONLY at mask's sparsity pattern ->
    SparseCooTensor (the reference's SDDMM-style masked matmul)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    mask = _as_coo(mask)
    rows = jnp.asarray(mask.indices[0])
    cols = jnp.asarray(mask.indices[1])

    def f(a, b):
        return jnp.sum(a[rows] * b[:, cols].T, axis=-1)   # [nnz]

    vals = run_op(f, [x, y], "sparse_masked_matmul")
    return SparseCooTensor(mask.indices, vals, mask.shape)


def _unary(fn, name):
    def op(x):
        was_csr = isinstance(x, SparseCsrTensor)
        coo = _as_coo(x)
        vals = run_op(fn, [coo.values], f"sparse_{name}")
        out = SparseCooTensor(coo.indices, vals, coo.shape)
        return out.to_sparse_csr() if was_csr else out

    op.__name__ = name
    return op


relu = _unary(lambda v: jnp.maximum(v, 0), "relu")
abs = _unary(jnp.abs, "abs")  # noqa: A001
sin = _unary(jnp.sin, "sin")
tanh = _unary(jnp.tanh, "tanh")
sqrt = _unary(jnp.sqrt, "sqrt")
square = _unary(jnp.square, "square")
neg = _unary(jnp.negative, "neg")


def pow(x, factor):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor), "pow")(x)


def cast_values(values, dtype):
    from ..core.dtype import convert_dtype
    dt = convert_dtype(dtype)
    return run_op(lambda v: v.astype(dt), [ensure_tensor(values)],
                  "sparse_cast")


def cast(x, index_dtype=None, value_dtype=None):
    was_csr = isinstance(x, SparseCsrTensor)
    coo = _as_coo(x)
    vals = cast_values(coo.values, value_dtype) if value_dtype else coo.values
    out = SparseCooTensor(coo.indices, vals, coo.shape)
    if was_csr:
        out = out.to_sparse_csr()
        if index_dtype is not None:
            out.crows = out.crows.astype(np.dtype(index_dtype))
            out.cols = out.cols.astype(np.dtype(index_dtype))
    elif index_dtype is not None:
        # set after construction: __init__ normalizes to int64
        out.indices = out.indices.astype(np.dtype(index_dtype))
    return out


def coalesce(x):
    return x.coalesce()


def transpose(x, perm):
    was_csr = isinstance(x, SparseCsrTensor)
    coo = _as_coo(x)
    if len(perm) != len(coo.shape):
        raise ValueError("transpose perm rank mismatch")
    new_idx = coo.indices[list(perm)]
    new_shape = [coo.shape[p] for p in perm]
    out = SparseCooTensor(new_idx, coo.values, new_shape)
    return out.to_sparse_csr() if was_csr else out
