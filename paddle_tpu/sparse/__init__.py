"""paddle.sparse parity (COO/CSR tensors).

Reference parity: `phi/core/sparse_coo_tensor.h` / `sparse_csr_tensor.h` +
`python/paddle/sparse`. TPU note: XLA has no native sparse kernels; COO ops
lower to scatter/gather (same as the reference's GPU fallbacks for most ops).
Backed by `jax.experimental.sparse.BCOO` where available.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) else Tensor(jnp.asarray(indices))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        dense = jnp.zeros(self.shape, dtype=self.values._value.dtype)
        idx = tuple(self.indices._value[i] for i in range(self.indices._value.shape[0]))
        return Tensor(dense.at[idx].add(self.values._value))

    def nnz(self):
        return self.values._value.shape[0]


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    indices = np.stack([rows, cols])
    return SparseCooTensor(indices, values, shape)


def to_dense(x):
    return x.to_dense()
