"""dygraph_to_static: AST-rewrite tensor control flow for @to_static.

Reference parity: `python/paddle/fluid/dygraph/dygraph_to_static/` —
`program_translator.py:775` (ProgramTranslator), `ifelse_transformer.py:1`,
`loop_transformer.py:1`, `convert_operators.py` (runtime dispatch). The
reference rewrites `if`/`while`/`for` over tensors into
conditional_block/while ops; here the rewrite targets `lax.cond` /
`lax.while_loop` through runtime-dispatch helpers, so the SAME transformed
code runs eagerly (plain Python control flow, full semantics) and under
`jax.jit` tracing (XLA control flow) — exactly the reference's
convert_ifelse/convert_while_loop design.

Rewrites applied:
  if/elif/else      -> convert_ifelse(test, true_fn, false_fn) with the
                       union of branch-assigned names as outputs
  while             -> convert_while(cond_fn, body_fn, loop_vars) with
                       body-assigned names as the carried loop vars
  for x in range(…) -> convert_for_range(start, stop, step, body_fn, vars)
  a and b / a or b  -> convert_logical_and/or(lambda: a, lambda: b)
  not a             -> convert_logical_not(a)

Limitations (mirroring the reference's documented ones): branches containing
return/break/continue are left as Python (static predicates only); loop
variables must be initialized before a tensor-predicate loop.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

__all__ = ["ast_transform", "convert_ifelse", "convert_while",
           "convert_for_range", "convert_logical_and", "convert_logical_or",
           "convert_logical_not"]


class _Undef:
    """Sentinel for loop/branch vars that had no value at region entry.

    Any USE fails loudly with UnboundLocalError (python semantics for a
    possibly-unbound local), while mere propagation through untaken
    branches stays legal — the reference's RETURN_NO_VALUE pattern."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "dy2static: variable used before assignment (bound in only one "
            "branch/loop body); initialize it before the control flow")

    __bool__ = __int__ = __float__ = __iter__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __call__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _raise
    __hash__ = object.__hash__  # identity hash despite custom __eq__

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        self._raise()


UNDEF = _Undef()


def maybe(thunk):
    """Evaluate thunk; UNDEF if the name is not bound yet."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEF


def _unwrap(x):
    from ..core.tensor import Tensor
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _is_traced(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return isinstance(x, jax.core.Tracer)


def _as_bool_array(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return jnp.asarray(x).reshape(()).astype(bool)


# ---------------- runtime dispatch (convert_operators.py parity) ----------
def convert_ifelse(pred, true_fn, false_fn, init_vars, names):
    """Branch fns take the current values of the output names as args (a
    name that is read-then-written inside a branch must arrive as a
    parameter, not through the closure)."""
    if not _is_traced(pred):
        return true_fn(*init_vars) if pred else false_fn(*init_vars)

    def _chk(vals):
        # raise the friendly error DURING branch tracing — before lax.cond
        # chokes on an UNDEF leaf with a cryptic tree-mismatch TypeError
        seq = vals if isinstance(vals, (list, tuple)) else (vals,)
        for n, v in zip(names, seq):
            if v is UNDEF:
                raise ValueError(
                    f"dy2static: variable '{n}' is assigned in only one "
                    "branch of a tensor-predicate `if`; initialize it before "
                    "the branch")
        return vals

    from ..static.nn import cond
    return cond(pred, lambda: _chk(true_fn(*init_vars)),
                lambda: _chk(false_fn(*init_vars)))


def convert_while(cond_fn, body_fn, loop_vars, names):
    # A static (python) predicate unrolls under trace — required when the
    # body indexes layers by the counter; only a traced predicate lowers to
    # lax.while_loop.
    c0 = cond_fn(*loop_vars)
    if not _is_traced(c0):
        vs = list(loop_vars)
        while cond_fn(*vs):
            out = body_fn(*vs)
            vs = list(out) if isinstance(out, (list, tuple)) else [out]
        return tuple(vs)
    for n, v in zip(names, loop_vars):
        if v is UNDEF:
            raise ValueError(
                f"dy2static: loop variable '{n}' must be initialized before "
                "a tensor-predicate `while`")
    from ..static.nn import while_loop
    return tuple(while_loop(cond_fn, body_fn, list(loop_vars)))


def convert_for_range(start, stop, step, body_fn, target_init, loop_vars,
                      names):
    """body_fn(i, *vars) -> (i, *new_vars); returns (final_target, *vars).

    Static python bounds unroll (python `for`), even over traced loop vars —
    the counter stays a python int so `self.layers[i]` indexing works; only
    traced bounds lower to lax.while_loop. The loop target keeps python
    binding semantics: last iterated value, or its prior value when the
    loop body never runs."""
    traced = any(_is_traced(v) for v in (start, stop, step))
    if not traced:
        vs = list(loop_vars)
        last = target_init
        for i in range(int(start), int(stop), int(step)):
            last = i
            out = body_fn(i, *vs)
            vs = list(out[1:])
        return (last,) + tuple(vs)
    for n, v in zip(names, loop_vars):
        if v is UNDEF:
            raise ValueError(
                f"dy2static: loop variable '{n}' must be initialized before "
                "a tensor-bound `for range(...)`")
    from ..static.nn import while_loop

    # trip count with python-range semantics (negative steps included):
    # n = max(0, (stop - start + step -/+ 1) // step)
    a_ = jnp.asarray(_unwrap(start))
    b_ = jnp.asarray(_unwrap(stop))
    s_ = jnp.asarray(_unwrap(step))
    adj = jnp.where(s_ > 0, s_ - 1, s_ + 1)
    n_trips = jnp.maximum(0, (b_ - a_ + adj) // s_)

    def c(k, i, *vs):
        return _as_bool_array(k < n_trips)

    def b(k, i, *vs):
        out = body_fn(i, *vs)
        return (k + 1, _unwrap(out[0]) + s_) + tuple(out[1:])

    final = while_loop(c, b, [jnp.asarray(0), a_] + list(loop_vars))
    last = a_ + (n_trips - 1) * s_
    if target_init is not UNDEF:
        try:
            last = jnp.where(n_trips > 0, last, _unwrap(target_init))
        except TypeError:
            pass  # prior value not array-like: keep computed last
    return (last,) + tuple(final[2:])


def convert_logical_and(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs and rhs_fn()          # python short-circuit preserved
    return jnp.logical_and(_as_bool_array(lhs), _as_bool_array(rhs_fn()))


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs or rhs_fn()
    return jnp.logical_or(_as_bool_array(lhs), _as_bool_array(rhs_fn()))


def convert_logical_not(x):
    if not _is_traced(x):
        return not x
    return jnp.logical_not(_as_bool_array(x))


# ---------------- AST analysis ----------------
class _AssignedNames(ast.NodeVisitor):
    """Names (re)bound by statements — branch outputs / loop carries."""

    def __init__(self):
        self.names = set()

    def _target(self, t):
        if isinstance(t, ast.Name):
            if not t.id.startswith("__dy2s_"):
                self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
        elif isinstance(t, ast.Starred):
            self._target(t.value)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):  # walrus: (y := expr)
        self._target(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._target(node.optional_vars)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if not node.name.startswith("__dy2s_"):
            self.names.add(node.name)  # don't descend: inner scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasCtrlEscape(ast.NodeVisitor):
    """Return/break/continue at this statement level (not nested defs)."""

    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _escapes(stmts):
    v = _HasCtrlEscape()
    for s in stmts:
        v.visit(s)
    return v.found


def _name(id_, ctx):
    return ast.Name(id=id_, ctx=ctx)


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name("_jst", ast.Load()), attr=fn_name,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _maybe_expr(varname):
    # _jst.maybe(lambda: var)
    return _jst_call("maybe", [ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                           kw_defaults=[], kwarg=None, defaults=[]),
        body=_name(varname, ast.Load()))])


def _names_tuple_store(names):
    return ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                     ctx=ast.Store())


def _names_tuple_load(names):
    return ast.Tuple(elts=[_name(n, ast.Load()) for n in names],
                     ctx=ast.Load())


def _str_tuple(names):
    return ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load())


def _fn_def(name, argnames, body, returns_names):
    body = list(body)
    body.append(ast.Return(value=_names_tuple_load(returns_names)))
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a) for a in argnames],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body, decorator_list=[], returns=None)


class _Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- if / elif / else --
    def visit_If(self, node):
        self.generic_visit(node)
        if _escapes(node.body) or _escapes(node.orelse):
            return node
        names = sorted(_assigned(node.body) | _assigned(node.orelse))
        i = self._uid()
        tname, fname = f"__dy2s_true_{i}", f"__dy2s_false_{i}"
        true_def = _fn_def(tname, names, node.body, names)
        false_def = _fn_def(fname, names, node.orelse or [ast.Pass()], names)
        init = ast.List(elts=[_maybe_expr(n) for n in names], ctx=ast.Load())
        call = _jst_call("convert_ifelse",
                         [node.test, _name(tname, ast.Load()),
                          _name(fname, ast.Load()), init, _str_tuple(names)])
        if names:
            assign = ast.Assign(targets=[_names_tuple_store(names)], value=call)
        else:
            assign = ast.Expr(value=call)
        return [true_def, false_def, assign]

    # -- while --
    def visit_While(self, node):
        self.generic_visit(node)
        if _escapes(node.body) or node.orelse:
            return node
        names = sorted(_assigned(node.body))
        if not names:
            return node
        i = self._uid()
        cname, bname = f"__dy2s_cond_{i}", f"__dy2s_body_{i}"
        cond_def = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=a) for a in names],
                               vararg=None, kwonlyargs=[], kw_defaults=[],
                               kwarg=None, defaults=[]),
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        body_def = _fn_def(bname, names, node.body, names)
        init = ast.List(elts=[_maybe_expr(n) for n in names], ctx=ast.Load())
        call = _jst_call("convert_while",
                         [_name(cname, ast.Load()), _name(bname, ast.Load()),
                          init, _str_tuple(names)])
        assign = ast.Assign(targets=[_names_tuple_store(names)], value=call)
        return [cond_def, body_def, assign]

    # -- for target in range(...) --
    def visit_For(self, node):
        self.generic_visit(node)
        if (_escapes(node.body) or node.orelse
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or not isinstance(node.target, ast.Name)):
            return node
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(value=0), rargs[0], ast.Constant(value=1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(value=1)
        elif len(rargs) == 3:
            start, stop, step = rargs
        else:
            return node
        tvar = node.target.id
        names = sorted(_assigned(node.body) - {tvar})
        i = self._uid()
        bname = f"__dy2s_forbody_{i}"
        body_def = _fn_def(bname, [tvar] + names, node.body, [tvar] + names)
        init = ast.List(elts=[_maybe_expr(n) for n in names], ctx=ast.Load())
        call = _jst_call("convert_for_range",
                         [start, stop, step, _name(bname, ast.Load()),
                          _maybe_expr(tvar), init, _str_tuple(names)])
        assign = ast.Assign(targets=[_names_tuple_store([tvar] + names)],
                            value=call)
        return [body_def, assign]

    # -- boolean operators --
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        out = node.values[-1]
        for val in reversed(node.values[:-1]):
            out = _jst_call(fn, [
                ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                              vararg=None, kwonlyargs=[],
                                              kw_defaults=[], kwarg=None,
                                              defaults=[]),
                           body=val),
                ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                              vararg=None, kwonlyargs=[],
                                              kw_defaults=[], kwarg=None,
                                              defaults=[]),
                           body=out)])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node


def _has_ctrl_flow(tree) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, (ast.If, ast.While, ast.For, ast.BoolOp)):
            return True
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
            return True
    return False


def ast_transform(func):
    """Return `func` rewritten with tensor-aware control flow, or `func`
    unchanged when there is nothing to rewrite or the source is unavailable.
    Bound methods are re-bound to the same instance."""
    is_method = inspect.ismethod(func)
    fn = func.__func__ if is_method else func
    if isinstance(fn, functools.partial) or not isinstance(
            fn, types.FunctionType):
        return func
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return func
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return func
    if not _has_ctrl_flow(fdef):
        return func
    fdef.decorator_list = []  # avoid re-applying @to_static etc.
    new_tree = _Dy2StaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    try:
        code = compile(new_tree, filename=f"<dy2static:{fn.__name__}>",
                       mode="exec")
    except (SyntaxError, ValueError):
        return func

    closure_vals = {}
    if fn.__closure__:
        for cname, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                closure_vals[cname] = cell.cell_contents
            except ValueError:
                return func  # empty cell (e.g. recursive def): bail out
    import paddle_tpu.jit.dy2static as _self

    ns = {}
    inner_name = fn.__name__

    def _sync():
        # Live view of the defining module: names defined/rebound AFTER
        # decoration (forward-referenced helpers, monkeypatches) must stay
        # visible, so refresh before each call instead of snapshotting once.
        ns.update(fn.__globals__)
        ns.update(closure_vals)
        ns["_jst"] = _self

    _sync()
    exec(code, ns)
    inner = ns[inner_name]

    def new_fn(*args, **kwargs):
        _sync()
        ns[inner_name] = inner  # recursion resolves to the rewritten fn
        return inner(*args, **kwargs)

    functools.update_wrapper(new_fn, fn)
    new_fn.__dy2static_original__ = fn
    if is_method:
        return new_fn.__get__(func.__self__)
    return new_fn
