"""dygraph_to_static: AST-rewrite tensor control flow for @to_static.

Reference parity: `python/paddle/fluid/dygraph/dygraph_to_static/` —
`program_translator.py:775` (ProgramTranslator), `ifelse_transformer.py:1`,
`loop_transformer.py:1`, `convert_operators.py` (runtime dispatch). The
reference rewrites `if`/`while`/`for` over tensors into
conditional_block/while ops; here the rewrite targets `lax.cond` /
`lax.while_loop` through runtime-dispatch helpers, so the SAME transformed
code runs eagerly (plain Python control flow, full semantics) and under
`jax.jit` tracing (XLA control flow) — exactly the reference's
convert_ifelse/convert_while_loop design.

Rewrites applied:
  if/elif/else      -> convert_ifelse(test, true_fn, false_fn) with the
                       union of branch-assigned names as outputs
  while             -> convert_while(cond_fn, body_fn, loop_vars) with
                       body-assigned names as the carried loop vars
  for x in range(…) -> convert_for_range(start, stop, step, body_fn, vars)
  a and b / a or b  -> convert_logical_and/or(lambda: a, lambda: b)
  not a             -> convert_logical_not(a)

  break/continue     -> guard flags: `break` becomes `_dy2s_brk_i = True`
                       (loop test gains `and not _dy2s_brk_i`), `continue`
                       becomes `_dy2s_cont_i = True`, and the statements
                       after the escape are wrapped in `if not flag:` —
                       the reference's break_continue_transformer.py:1
                       lowering, landing on lax-compatible carried bools
  return             -> tail `if c: return a / return b` fuses into
                       if/else; returns under loops lower to
                       (_dy2s_ret_flag, _dy2s_ret_val) guard flags with
                       flag-aware loop tests — return_transformer.py:1

Limitations: loop variables must be initialized before a tensor-predicate
loop; a traced early-return's value must be type-joinable with the other
paths (the reference's RETURN_NO_VALUE magic-number scheme has the same
constraint, enforced at lax.cond/while typing instead).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

__all__ = ["ast_transform", "convert_ifelse", "convert_while",
           "convert_for_range", "convert_logical_and", "convert_logical_or",
           "convert_logical_not"]


class _Undef:
    """Sentinel for loop/branch vars that had no value at region entry.

    Any USE fails loudly with UnboundLocalError (python semantics for a
    possibly-unbound local), while mere propagation through untaken
    branches stays legal — the reference's RETURN_NO_VALUE pattern."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "dy2static: variable used before assignment (bound in only one "
            "branch/loop body); initialize it before the control flow")

    __bool__ = __int__ = __float__ = __iter__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __call__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _raise
    __hash__ = object.__hash__  # identity hash despite custom __eq__

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        self._raise()


UNDEF = _Undef()


def maybe(thunk):
    """Evaluate thunk; UNDEF if the name is not bound yet."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEF


def _unwrap(x):
    from ..core.tensor import Tensor
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _is_traced(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return isinstance(x, jax.core.Tracer)


def _as_bool_array(x):
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        x = x._value
    return jnp.asarray(x).reshape(()).astype(bool)


# ---------------- runtime dispatch (convert_operators.py parity) ----------
def convert_ifelse(pred, true_fn, false_fn, init_vars, names):
    """Branch fns take the current values of the output names as args (a
    name that is read-then-written inside a branch must arrive as a
    parameter, not through the closure)."""
    if not _is_traced(pred):
        return true_fn(*init_vars) if pred else false_fn(*init_vars)

    def _chk(vals):
        # raise the friendly error DURING branch tracing — before lax.cond
        # chokes on an UNDEF leaf with a cryptic tree-mismatch TypeError
        seq = vals if isinstance(vals, (list, tuple)) else (vals,)
        for n, v in zip(names, seq):
            if v is UNDEF:
                raise ValueError(
                    f"dy2static: variable '{n}' is assigned in only one "
                    "branch of a tensor-predicate `if`; initialize it before "
                    "the branch")
        return vals

    from ..static.nn import cond

    try:
        return cond(pred, lambda: _chk(true_fn(*init_vars)),
                    lambda: _chk(false_fn(*init_vars)))
    except TypeError:
        if not (names and any(n.startswith("_dy2s_") for n in names)):
            raise

    # Pytree mismatch on a lowered escape: a _dy2s_* var (e.g. the return
    # value) is None on the untaken side. Probe both branches for a type
    # template and backfill the None side with a typed zero — dead by
    # construction: the flag protocol guarantees a real assignment happens
    # before the value is consumed (return_transformer.py's
    # RETURN_NO_VALUE magic-number scheme, typed instead). The probe cost
    # (one extra branch trace) is only paid on this repair path.
    fixes = {}
    probe_t = true_fn(*init_vars)
    probe_f = false_fn(*init_vars)
    seq_t = probe_t if isinstance(probe_t, tuple) else (probe_t,)
    seq_f = probe_f if isinstance(probe_f, tuple) else (probe_f,)
    for i, (n, a, b) in enumerate(zip(names, seq_t, seq_f)):
        if not n.startswith("_dy2s_"):
            continue
        a_none, b_none = a is None or a is UNDEF, b is None or b is UNDEF
        if a_none != b_none:
            tmpl = _unwrap(b if a_none else a)
            if hasattr(tmpl, "shape"):
                fixes[i] = jnp.zeros(jnp.shape(tmpl), jnp.result_type(tmpl))

    def _fix(vals):
        if not fixes:
            return _chk(vals)
        seq = list(vals) if isinstance(vals, tuple) else [vals]
        for i, z in fixes.items():
            if seq[i] is None or seq[i] is UNDEF:
                seq[i] = z
        out = tuple(seq) if isinstance(vals, tuple) else seq[0]
        return _chk(out)

    return cond(pred, lambda: _fix(true_fn(*init_vars)),
                lambda: _fix(false_fn(*init_vars)))


def convert_while(cond_fn, body_fn, loop_vars, names):
    # A static (python) predicate unrolls under trace — required when the
    # body indexes layers by the counter; only a traced predicate lowers to
    # lax.while_loop.
    def _lax_loop(vs):
        for n, v in zip(names, vs):
            if v is UNDEF:
                raise ValueError(
                    f"dy2static: loop variable '{n}' must be initialized "
                    "before a tensor-predicate `while`")
        from ..static.nn import while_loop
        return tuple(while_loop(cond_fn, body_fn, list(vs)))

    c0 = cond_fn(*loop_vars)
    if not _is_traced(c0):
        vs = list(loop_vars)
        while True:
            c = cond_fn(*vs)
            if _is_traced(c):
                # the predicate BECAME traced mid-loop (e.g. a lowered
                # break flag fed by a tensor `if`): hand the current
                # carries to lax for the remaining iterations
                return _lax_loop(vs)
            if not c:
                break
            out = body_fn(*vs)
            vs = list(out) if isinstance(out, (list, tuple)) else [out]
        return tuple(vs)
    return _lax_loop(loop_vars)


def convert_for_range(start, stop, step, body_fn, target_init, loop_vars,
                      names):
    """body_fn(i, *vars) -> (i, *new_vars); returns (final_target, *vars).

    Static python bounds unroll (python `for`), even over traced loop vars —
    the counter stays a python int so `self.layers[i]` indexing works; only
    traced bounds lower to lax.while_loop. The loop target keeps python
    binding semantics: last iterated value, or its prior value when the
    loop body never runs."""
    traced = any(_is_traced(v) for v in (start, stop, step))
    if not traced:
        vs = list(loop_vars)
        last = target_init
        for i in range(int(start), int(stop), int(step)):
            last = i
            out = body_fn(i, *vs)
            vs = list(out[1:])
        return (last,) + tuple(vs)
    for n, v in zip(names, loop_vars):
        if v is UNDEF:
            raise ValueError(
                f"dy2static: loop variable '{n}' must be initialized before "
                "a tensor-bound `for range(...)`")
    from ..static.nn import while_loop

    # trip count with python-range semantics (negative steps included):
    # n = max(0, (stop - start + step -/+ 1) // step)
    a_ = jnp.asarray(_unwrap(start))
    b_ = jnp.asarray(_unwrap(stop))
    s_ = jnp.asarray(_unwrap(step))
    adj = jnp.where(s_ > 0, s_ - 1, s_ + 1)
    n_trips = jnp.maximum(0, (b_ - a_ + adj) // s_)

    def c(k, i, *vs):
        return _as_bool_array(k < n_trips)

    def b(k, i, *vs):
        out = body_fn(i, *vs)
        return (k + 1, _unwrap(out[0]) + s_) + tuple(out[1:])

    final = while_loop(c, b, [jnp.asarray(0), a_] + list(loop_vars))
    last = a_ + (n_trips - 1) * s_
    if target_init is not UNDEF:
        try:
            last = jnp.where(n_trips > 0, last, _unwrap(target_init))
        except TypeError:
            pass  # prior value not array-like: keep computed last
    return (last,) + tuple(final[2:])


def convert_logical_and(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs and rhs_fn()          # python short-circuit preserved
    return jnp.logical_and(_as_bool_array(lhs), _as_bool_array(rhs_fn()))


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if not _is_traced(lhs):
        return lhs or rhs_fn()
    return jnp.logical_or(_as_bool_array(lhs), _as_bool_array(rhs_fn()))


def convert_logical_not(x):
    if not _is_traced(x):
        return not x
    return jnp.logical_not(_as_bool_array(x))


# ---------------- AST analysis ----------------
class _AssignedNames(ast.NodeVisitor):
    """Names (re)bound by statements — branch outputs / loop carries."""

    def __init__(self):
        self.names = set()

    def _target(self, t):
        if isinstance(t, ast.Name):
            if not t.id.startswith("__dy2s_"):
                self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
        elif isinstance(t, ast.Starred):
            self._target(t.value)

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):  # walrus: (y := expr)
        self._target(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._target(node.optional_vars)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if not node.name.startswith("__dy2s_"):
            self.names.add(node.name)  # don't descend: inner scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _HasCtrlEscape(ast.NodeVisitor):
    """Return/break/continue at this statement level (not nested defs)."""

    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _escapes(stmts):
    v = _HasCtrlEscape()
    for s in stmts:
        v.visit(s)
    return v.found


def _name(id_, ctx):
    return ast.Name(id=id_, ctx=ctx)


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name("_jst", ast.Load()), attr=fn_name,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _maybe_expr(varname):
    # _jst.maybe(lambda: var)
    return _jst_call("maybe", [ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                           kw_defaults=[], kwarg=None, defaults=[]),
        body=_name(varname, ast.Load()))])


def _names_tuple_store(names):
    return ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                     ctx=ast.Store())


def _names_tuple_load(names):
    return ast.Tuple(elts=[_name(n, ast.Load()) for n in names],
                     ctx=ast.Load())


def _str_tuple(names):
    return ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load())


def _fn_def(name, argnames, body, returns_names):
    body = list(body)
    body.append(ast.Return(value=_names_tuple_load(returns_names)))
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a) for a in argnames],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body, decorator_list=[], returns=None)


# ---------------- escape lowering (break/continue/return) ----------------

def _sets_name(stmt, name):
    """Does stmt's subtree (sans nested defs) assign `name`?"""
    for n in ast.walk(stmt):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
                and n is not stmt:
            continue
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
    return False


def _not_name(name):
    return ast.UnaryOp(op=ast.Not(), operand=_name(name, ast.Load()))


def _assign_const(name, value):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(value=value))


def _guard_tail(stmts, flag):
    """After any statement that may set `flag`, wrap the rest of the block
    in `if not flag:` — recursively, including inside nested `if` arms
    (loops and nested defs are scope boundaries handled by their own
    lowering passes)."""
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.If):
            s.body = _guard_tail(s.body, flag)
            s.orelse = _guard_tail(s.orelse, flag)
        out.append(s)
        if _sets_name(s, flag) and i + 1 < len(stmts):
            rest = _guard_tail(stmts[i + 1:], flag)
            out.append(ast.If(test=_not_name(flag), body=rest, orelse=[]))
            return out
    return out


def _ends_in_return(stmts):
    return bool(stmts) and isinstance(stmts[-1], (ast.Return, ast.Raise))


def _always_returns(stmts):
    """Every path through stmts ends in return/raise (shallow analysis)."""
    if _ends_in_return(stmts):
        return True
    if stmts and isinstance(stmts[-1], ast.If):
        last = stmts[-1]
        return bool(last.orelse) and _always_returns(last.body) \
            and _always_returns(last.orelse)
    return False


class _TailReturnFusion(ast.NodeTransformer):
    """`if c: ...return` followed by more statements -> push the rest into
    the else branch. Turns the ubiquitous early-return pattern into a
    well-typed if/else join with no guard flags needed
    (return_transformer.py's simplest case)."""

    def _fuse_block(self, stmts):
        stmts = list(stmts)
        changed = True
        while changed:
            changed = False
            for i, s in enumerate(stmts):
                if isinstance(s, ast.If) and _always_returns(s.body) \
                        and not s.orelse and i + 1 < len(stmts):
                    s.orelse = self._fuse_block(stmts[i + 1:])
                    stmts = stmts[:i + 1]
                    changed = True
                    break
        return stmts

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        node.body = self._fuse_block(node.body)
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        node.body = self._fuse_block(node.body)
        node.orelse = self._fuse_block(node.orelse)
        return node


def _strip_tail_returns(stmts, var):
    """Replace the terminal Return on every path of an always-returning
    block with an assignment to `var`."""
    last = stmts[-1]
    if isinstance(last, ast.Return):
        val = last.value if last.value is not None else ast.Constant(value=None)
        stmts[-1] = ast.Assign(targets=[_name(var, ast.Store())], value=val)
    elif isinstance(last, ast.If):
        _strip_tail_returns(last.body, var)
        _strip_tail_returns(last.orelse, var)
    return stmts


class _ReturnPushdown(ast.NodeTransformer):
    """A block ending in an If where BOTH arms always return becomes
    branch-assignments of one fresh var + a single trailing return — a
    well-typed lax.cond join with no guard flags (the structured half of
    return_transformer.py; _ReturnLowering handles the rest)."""

    def __init__(self, uid):
        self._uid = uid

    def _push_block(self, stmts):
        if not stmts:
            return stmts
        last = stmts[-1]
        if isinstance(last, ast.If) and last.orelse \
                and _always_returns(last.body) and _always_returns(last.orelse) \
                and not isinstance(last.body[-1], ast.Raise) \
                and not isinstance(last.orelse[-1], ast.Raise):
            var = f"_dy2s_ret_{self._uid()}"
            _strip_tail_returns(last.body, var)
            _strip_tail_returns(last.orelse, var)
            return stmts + [ast.Return(value=_name(var, ast.Load()))]
        return stmts

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        node.body = self._push_block(node.body)
        return node


class _ForRangeToWhile(ast.NodeTransformer):
    """for x in range(...) containing break/continue/return -> explicit
    while, so the guard-flag lowering has a test to AND flags into. The
    increment is tagged so continue-guards leave it outside."""

    def __init__(self, uid):
        self._uid = uid

    def _visit_block(self, stmts):
        out = []
        for s in stmts:
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        return out

    def generic_visit(self, node):
        for field, old in ast.iter_fields(node):
            if isinstance(old, list) and old and isinstance(old[0], ast.stmt):
                setattr(node, field, self._visit_block(old))
            elif isinstance(old, ast.AST):
                self.visit(old)
        return node

    def visit_For(self, node):
        self.generic_visit(node)
        if (not _escapes(node.body) or node.orelse
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or not isinstance(node.target, ast.Name)):
            return node
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(value=0), rargs[0], \
                ast.Constant(value=1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(value=1)
        elif len(rargs) == 3:
            start, stop, step = rargs
        else:
            return node
        i = self._uid()
        it, st, sp = f"_dy2s_it_{i}", f"_dy2s_stop_{i}", f"_dy2s_step_{i}"

        def nm(x, ctx=ast.Load):
            return _name(x, ctx())

        # ((step > 0) and (it < stop)) or ((step < 0) and (it > stop))
        test = ast.BoolOp(op=ast.Or(), values=[
            ast.BoolOp(op=ast.And(), values=[
                ast.Compare(left=nm(sp), ops=[ast.Gt()],
                            comparators=[ast.Constant(value=0)]),
                ast.Compare(left=nm(it), ops=[ast.Lt()],
                            comparators=[nm(st)])]),
            ast.BoolOp(op=ast.And(), values=[
                ast.Compare(left=nm(sp), ops=[ast.Lt()],
                            comparators=[ast.Constant(value=0)]),
                ast.Compare(left=nm(it), ops=[ast.Gt()],
                            comparators=[nm(st)])])])
        incr = ast.Assign(
            targets=[nm(it, ast.Store)],
            value=ast.BinOp(left=nm(it), op=ast.Add(), right=nm(sp)))
        incr._dy2s_incr = True
        body = [ast.Assign(targets=[_name(node.target.id, ast.Store())],
                           value=nm(it))] + node.body + [incr]
        return [
            ast.Assign(targets=[nm(it, ast.Store)], value=start),
            ast.Assign(targets=[nm(st, ast.Store)], value=stop),
            ast.Assign(targets=[nm(sp, ast.Store)], value=step),
            ast.While(test=test, body=body, orelse=[]),
        ]


class _ReturnLowering(ast.NodeTransformer):
    """Returns under control flow -> (_dy2s_ret_flag, _dy2s_ret_val) with
    guarded tails and flag-aware while tests (return_transformer.py:1)."""

    FLAG, VAL = "_dy2s_ret_flag", "_dy2s_ret_val"

    def lower(self, fdef):
        # A Return inside a surviving `for` over a NON-range iterable can't
        # be flag-lowered (no test expression to AND the flag into) — leave
        # the whole function on python-escape semantics rather than lower
        # partially and keep iterating past the "return".
        for n in ast.walk(fdef):
            if isinstance(n, ast.For):
                if any(isinstance(m, (ast.Return, ast.Break, ast.Continue))
                       for m in ast.walk(n)):
                    return fdef
        inside = False
        for n in ast.walk(fdef):
            if isinstance(n, (ast.If, ast.While, ast.For)):
                if any(isinstance(m, ast.Return) for m in ast.walk(n)):
                    inside = True
                    break
        if not inside:
            return fdef
        self._replace_block(fdef)
        fdef.body = [_assign_const(self.FLAG, False),
                     _assign_const(self.VAL, None)] + \
            self._guard_blocks(fdef).body
        fdef.body.append(ast.Return(value=_name(self.VAL, ast.Load())))
        return fdef

    # pass 1: every Return -> val/flag assignment
    def _replace_block(self, root):
        class R(ast.NodeTransformer):
            def visit_Return(self, node):
                val = node.value if node.value is not None \
                    else ast.Constant(value=None)
                return [
                    ast.Assign(targets=[_name(_ReturnLowering.VAL,
                                              ast.Store())], value=val),
                    _assign_const(_ReturnLowering.FLAG, True),
                ]

            def visit_FunctionDef(self, node):
                return node  # inner scope

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                return node

        for field, old in ast.iter_fields(root):
            if isinstance(old, list):
                new = []
                for s in old:
                    if isinstance(s, ast.stmt):
                        r = R().visit(s)
                        new.extend(r if isinstance(r, list) else [r])
                    else:
                        new.append(s)
                setattr(root, field, new)
        for child in ast.iter_child_nodes(root):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)) or child is root:
                self._replace_block(child)
        return root

    # pass 2: guard tails + while tests
    def _guard_blocks(self, root):
        for child in ast.iter_child_nodes(root):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                self._guard_blocks(child)
        for field, old in ast.iter_fields(root):
            if isinstance(old, list) and old and isinstance(old[0], ast.stmt):
                setattr(root, field, _guard_tail(old, self.FLAG))
        if isinstance(root, ast.While) and _sets_name(root, self.FLAG):
            root.test = ast.BoolOp(op=ast.And(),
                                   values=[root.test, _not_name(self.FLAG)])
        return root


class _BreakContinueLowering(ast.NodeTransformer):
    """Per-loop guard flags (break_continue_transformer.py:1). Runs after
    return lowering, so remaining Break/Continue nodes at a loop's level
    (nested loops already lowered) belong to that loop."""

    def __init__(self, uid):
        self._uid = uid

    def _visit_block(self, stmts):
        out = []
        for s in stmts:
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        return out

    def generic_visit(self, node):
        for field, old in ast.iter_fields(node):
            if isinstance(old, list) and old and isinstance(old[0], ast.stmt):
                setattr(node, field, self._visit_block(old))
            elif isinstance(old, ast.AST):
                self.visit(old)
        return node

    def visit_While(self, node):
        self.generic_visit(node)  # inner loops first
        has_b = any(isinstance(n, ast.Break) for n in ast.walk(node))
        has_c = any(isinstance(n, ast.Continue) for n in ast.walk(node))
        if not (has_b or has_c):
            return node
        i = self._uid()
        brk, cont = f"_dy2s_brk_{i}", f"_dy2s_cont_{i}"

        class R(ast.NodeTransformer):
            def visit_Break(self, n):
                return _assign_const(brk, True)

            def visit_Continue(self, n):
                return _assign_const(cont, True)

            def visit_While(self, n):
                return n  # inner loops already lowered; don't descend

            def visit_For(self, n):
                return n

            def visit_FunctionDef(self, n):
                return n

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, n):
                return n

        body = [R().visit(s) for s in node.body]
        # keep a tagged for->while increment outside the continue guards
        tail = []
        if body and getattr(body[-1], "_dy2s_incr", False):
            tail = [body[-1]]
            body = body[:-1]
        if has_c:
            body = _guard_tail(body, cont)
        if has_b:
            body = _guard_tail(body, brk)
        pre = []
        if has_c:
            body = [_assign_const(cont, False)] + body
        if has_b:
            pre.append(_assign_const(brk, False))
            node.test = ast.BoolOp(op=ast.And(),
                                   values=[node.test, _not_name(brk)])
        node.body = body + tail
        return pre + [node] if pre else node


def _lower_escapes(tree, uid):
    """break/continue/return -> structured control flow + guard flags."""
    tree = _TailReturnFusion().visit(tree)
    tree = _ReturnPushdown(uid).visit(tree)
    tree = _ForRangeToWhile(uid).visit(tree)
    fdef = tree.body[0]
    _ReturnLowering().lower(fdef)
    tree = _BreakContinueLowering(uid).visit(tree)
    return tree


class _Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- if / elif / else --
    def visit_If(self, node):
        self.generic_visit(node)
        if _escapes(node.body) or _escapes(node.orelse):
            return node
        names = sorted(_assigned(node.body) | _assigned(node.orelse))
        i = self._uid()
        tname, fname = f"__dy2s_true_{i}", f"__dy2s_false_{i}"
        true_def = _fn_def(tname, names, node.body, names)
        false_def = _fn_def(fname, names, node.orelse or [ast.Pass()], names)
        init = ast.List(elts=[_maybe_expr(n) for n in names], ctx=ast.Load())
        call = _jst_call("convert_ifelse",
                         [node.test, _name(tname, ast.Load()),
                          _name(fname, ast.Load()), init, _str_tuple(names)])
        if names:
            assign = ast.Assign(targets=[_names_tuple_store(names)], value=call)
        else:
            assign = ast.Expr(value=call)
        return [true_def, false_def, assign]

    # -- while --
    def visit_While(self, node):
        self.generic_visit(node)
        if _escapes(node.body) or node.orelse:
            return node
        names = sorted(_assigned(node.body))
        if not names:
            return node
        i = self._uid()
        cname, bname = f"__dy2s_cond_{i}", f"__dy2s_body_{i}"
        cond_def = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=a) for a in names],
                               vararg=None, kwonlyargs=[], kw_defaults=[],
                               kwarg=None, defaults=[]),
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        body_def = _fn_def(bname, names, node.body, names)
        init = ast.List(elts=[_maybe_expr(n) for n in names], ctx=ast.Load())
        call = _jst_call("convert_while",
                         [_name(cname, ast.Load()), _name(bname, ast.Load()),
                          init, _str_tuple(names)])
        assign = ast.Assign(targets=[_names_tuple_store(names)], value=call)
        return [cond_def, body_def, assign]

    # -- for target in range(...) --
    def visit_For(self, node):
        self.generic_visit(node)
        if (_escapes(node.body) or node.orelse
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or not isinstance(node.target, ast.Name)):
            return node
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(value=0), rargs[0], ast.Constant(value=1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(value=1)
        elif len(rargs) == 3:
            start, stop, step = rargs
        else:
            return node
        tvar = node.target.id
        names = sorted(_assigned(node.body) - {tvar})
        i = self._uid()
        bname = f"__dy2s_forbody_{i}"
        body_def = _fn_def(bname, [tvar] + names, node.body, [tvar] + names)
        init = ast.List(elts=[_maybe_expr(n) for n in names], ctx=ast.Load())
        call = _jst_call("convert_for_range",
                         [start, stop, step, _name(bname, ast.Load()),
                          _maybe_expr(tvar), init, _str_tuple(names)])
        assign = ast.Assign(targets=[_names_tuple_store([tvar] + names)],
                            value=call)
        return [body_def, assign]

    # -- boolean operators --
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        out = node.values[-1]
        for val in reversed(node.values[:-1]):
            out = _jst_call(fn, [
                ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                              vararg=None, kwonlyargs=[],
                                              kw_defaults=[], kwarg=None,
                                              defaults=[]),
                           body=val),
                ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                              vararg=None, kwonlyargs=[],
                                              kw_defaults=[], kwarg=None,
                                              defaults=[]),
                           body=out)])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node


def _has_ctrl_flow(tree) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, (ast.If, ast.While, ast.For, ast.BoolOp)):
            return True
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
            return True
    return False


def ast_transform(func):
    """Return `func` rewritten with tensor-aware control flow, or `func`
    unchanged when there is nothing to rewrite or the source is unavailable.
    Bound methods are re-bound to the same instance."""
    is_method = inspect.ismethod(func)
    fn = func.__func__ if is_method else func
    if isinstance(fn, functools.partial) or not isinstance(
            fn, types.FunctionType):
        return func
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return func
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return func
    if not _has_ctrl_flow(fdef):
        return func
    fdef.decorator_list = []  # avoid re-applying @to_static etc.
    import itertools
    counter = itertools.count(1)
    tree = _lower_escapes(tree, lambda: next(counter))
    new_tree = _Dy2StaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    try:
        code = compile(new_tree, filename=f"<dy2static:{fn.__name__}>",
                       mode="exec")
    except (SyntaxError, ValueError):
        return func

    closure_vals = {}
    if fn.__closure__:
        for cname, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                closure_vals[cname] = cell.cell_contents
            except ValueError:
                return func  # empty cell (e.g. recursive def): bail out
    import paddle_tpu.jit.dy2static as _self

    ns = {}
    inner_name = fn.__name__

    def _sync():
        # Live view of the defining module: names defined/rebound AFTER
        # decoration (forward-referenced helpers, monkeypatches) must stay
        # visible, so refresh before each call instead of snapshotting once.
        ns.update(fn.__globals__)
        ns.update(closure_vals)
        ns["_jst"] = _self

    _sync()
    exec(code, ns)
    inner = ns[inner_name]

    def new_fn(*args, **kwargs):
        _sync()
        ns[inner_name] = inner  # recursion resolves to the rewritten fn
        return inner(*args, **kwargs)

    functools.update_wrapper(new_fn, fn)
    new_fn.__dy2static_original__ = fn
    if is_method:
        return new_fn.__get__(func.__self__)
    return new_fn
