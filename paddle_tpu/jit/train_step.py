"""Jitted whole-train-step builder — the TPU performance path.

Reference parity: this plays the role of the reference's static-graph
training program (forward + append_backward + optimizer ops compiled as one
ProgramDesc, SURVEY §3.1): ONE XLA executable for forward+backward+update,
with buffer donation on parameters and optimizer state (the XLA answer to
fluid's in-place Variable updates).

Usage:
    step = TrainStep(model, loss_fn, optimizer)
    loss = step(x, y)        # tensors in, python float-able loss out
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.tensor import Tensor
from .functional import functional_call, split_state


class TrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer, amp_dtype=None,
                 donate: bool = True, mesh=None, in_shardings=None,
                 n_model_inputs: Optional[int] = None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_dtype = amp_dtype
        self._jitted = None
        self._donate = donate
        self._slots = None
        self._pnames = None
        self._bnames = None
        # step(x..., y...): first n go to model.forward, the rest to loss_fn
        self._n_model_inputs = n_model_inputs

    def _build(self):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        trainable, frozen = split_state(model)
        self._pnames, self._bnames = list(trainable), list(frozen)
        ptensors = [trainable[n] for n in self._pnames]
        optimizer._parameter_list = optimizer._parameter_list or ptensors
        self._slots = optimizer.init_state(ptensors)
        pnames, bnames = self._pnames, self._bnames
        amp_dtype = self.amp_dtype

        def pure(params, slots, buffers, rng_key, lr, t, inputs, labels):
            rnd.push_trace_key(rng_key)
            try:
                def fwd(ps):
                    if amp_dtype is not None:
                        ps = [p.astype(amp_dtype)
                              if jnp.issubdtype(p.dtype, jnp.floating) else p
                              for p in ps]
                    out = functional_call(model, pnames, ps, bnames, buffers, *inputs)
                    outs = [Tensor(o) for o in out] if isinstance(out, (list, tuple)) \
                        else [Tensor(out)]
                    loss = loss_fn(*outs, *[Tensor(l) for l in labels])
                    return loss._value if isinstance(loss, Tensor) else loss

                loss, grads = jax.value_and_grad(fwd)(params)
                new_params, new_slots = optimizer.functional_update(
                    params, grads, slots, lr, t, params_meta=ptensors)
                return new_params, new_slots, loss
            finally:
                rnd.pop_trace_key()

        donate = (0, 1) if self._donate else ()
        self._jitted = jax.jit(pure, donate_argnums=donate)

    def __call__(self, *batch):
        """batch: input tensors consumed by model.forward; loss_fn receives the
        model output(s) — close labels into loss_fn or pass them as model inputs.
        """
        if self._jitted is None:
            self._build()
        trainable, frozen = split_state(self.model)
        params = [trainable[n]._value for n in self._pnames]
        buffers = [frozen[n]._value for n in self._bnames]
        arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        n_mi = self._n_model_inputs
        if n_mi is None:
            n_mi = len(arrs) if len(arrs) <= 1 else len(arrs) - 1
        inputs, labels = arrs[:n_mi], arrs[n_mi:]
        key = rnd.default_generator().next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.optimizer._step_count + 1, jnp.float32)
        new_params, self._slots, loss = self._jitted(params, self._slots, buffers, key,
                                                     lr, t, inputs, labels)
        for n, v in zip(self._pnames, new_params):
            trainable[n]._value = v
        self.optimizer._step_count += 1
        return Tensor(loss)
