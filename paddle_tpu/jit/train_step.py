"""Jitted whole-train-step builder — the TPU performance path.

Reference parity: this plays the role of the reference's static-graph
training program (forward + append_backward + optimizer ops compiled as one
ProgramDesc, SURVEY §3.1): ONE XLA executable for forward+backward+update,
with buffer donation on parameters and optimizer state (the XLA answer to
fluid's in-place Variable updates).

Usage:
    step = TrainStep(model, loss_fn, optimizer)
    loss = step(x, y)        # tensors in, python float-able loss out
"""
from __future__ import annotations

from typing import Callable, Optional

import time as _time

import jax
import jax.numpy as jnp

from .. import analysis as _analysis
from .. import monitor as _monitor
from .. import obs as _obs
from ..obs import memory as _mem
from ..core import compile_cache as _cc
from ..core import executable as _exe
from ..core import random as rnd
from ..core.tensor import Tensor
from .functional import functional_call, split_state


def raise_nonfinite(bad, pnames, context):
    """Decode the in-program finite flags ([P+1] or [n_steps, P+1]) and
    raise naming the offending grads (reference per-op abort,
    operator.cc:1171). No-op when the check wasn't traced (bad is None).
    Callers must have committed params/slots/step state FIRST — the jit
    call donated the old buffers."""
    if bad is None:
        return
    import numpy as np_
    flags_arr = np_.asarray(bad)
    if flags_arr.ndim == 2:              # scan: [n_steps, P+1] -> any step
        flags_arr = flags_arr.any(axis=0)
    if not flags_arr.any():
        return
    names = ["loss" if i == 0 else f"grad of {pnames[i - 1]}"
             for i in np_.nonzero(flags_arr)[0]]
    raise FloatingPointError(
        f"NaN/Inf detected in {context} "
        f"(FLAGS_check_nan_inf=True): {', '.join(names)}")


class TrainStep:
    def __init__(self, model, loss_fn: Callable, optimizer, amp_dtype=None,
                 donate: bool = True, mesh=None, in_shardings=None,
                 n_model_inputs: Optional[int] = None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_dtype = amp_dtype
        self._jitted = None
        self._donate = donate
        self._slots = None
        self._pnames = None
        self._bnames = None
        # step(x..., y...): first n go to model.forward, the rest to loss_fn
        self._n_model_inputs = n_model_inputs
        # executable substrate: signature ledger (novelty + retrace
        # accounting) and per-signature cached callables (persistent-cache
        # deserialized executables) — one implementation for all regimes
        self._ledger = _exe.ExecutableLedger("train_step")

    def _build(self):
        from ..core import flags as _flags
        if _monitor._ENABLED:
            _monitor.count("jit.train_step.builds")
        if _analysis._ENABLED:
            # trace-time tpu-lint on the functions about to be traced into
            # the step executable (build runs once; __call__ pays nothing)
            _analysis.lint_traced(getattr(self.model, "forward", self.model),
                                  "train_step")
            _analysis.lint_traced(self.loss_fn, "train_step")
        # FLAGS_check_nan_inf for the COMPILED hot loop (operator.cc:1171
        # role): the per-op eager scan can't see inside a jitted step, so
        # the finite-check is traced INTO the executable — one fused
        # [P+1]-flag reduction over loss+grads, read back on host only in
        # debug mode. Flag is captured at build time (first step).
        self._nan_check = bool(_flags.flag("check_nan_inf"))
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        trainable, frozen = split_state(model)
        self._pnames, self._bnames = list(trainable), list(frozen)
        ptensors = [trainable[n] for n in self._pnames]
        # cache tensor objects: __call__ must not re-walk the module tree
        self._ptensors = ptensors
        self._btensors = [frozen[n] for n in self._bnames]
        optimizer._parameter_list = optimizer._parameter_list or ptensors
        self._slots = optimizer.init_state(ptensors)
        pnames, bnames = self._pnames, self._bnames
        amp_dtype = self.amp_dtype

        def one_step(params, slots, buffers, step_key, lr, t, inputs, labels):
            rnd.push_trace_key(step_key)
            try:
                def fwd(ps):
                    from .functional import amp_functional_call
                    out = amp_functional_call(model, pnames, ps, bnames,
                                              buffers, inputs, amp_dtype)
                    outs = [Tensor(o) for o in out] if isinstance(out, (list, tuple)) \
                        else [Tensor(out)]
                    loss = loss_fn(*outs, *[Tensor(l) for l in labels])
                    return loss._value if isinstance(loss, Tensor) else loss

                loss, grads = jax.value_and_grad(fwd)(params)
                new_params, new_slots = optimizer.functional_update(
                    params, grads, slots, lr, t, params_meta=ptensors)
                if self._nan_check:
                    bad = jnp.stack(
                        [~jnp.isfinite(loss)]
                        + [~jnp.all(jnp.isfinite(g)) for g in grads])
                    return new_params, new_slots, loss, bad
                return new_params, new_slots, loss, None
            finally:
                rnd.pop_trace_key()

        def pure(params, slots, buffers, rng_key, lr, t, inputs, labels):
            # rng advance + step counter live IN the program: zero per-step
            # host->device scalar traffic (matters on remote/tunnel targets)
            step_key, carry_key = jax.random.split(rng_key)
            new_params, new_slots, loss, bad = one_step(
                params, slots, buffers, step_key, lr, t, inputs, labels)
            return new_params, new_slots, loss, carry_key, t + 1.0, bad

        def pure_scan(params, slots, buffers, rng_key, lr, t, inputs, labels):
            # Device-side training loop: N steps inside ONE executable via
            # lax.scan — the TPU answer to the reference's C++ trainer hot
            # loop (framework/trainer.h:59, hogwild_worker.cc TrainFiles),
            # which likewise iterates steps without returning to the host.
            # inputs/labels are stacked [n_steps, ...]; weights/opt state
            # stay device-resident across the whole span.
            def body(carry, xs):
                params, slots, key, t = carry
                ins, labs = xs
                step_key, key = jax.random.split(key)
                new_params, new_slots, loss, bad = one_step(
                    params, slots, buffers, step_key, lr, t, ins, labs)
                return (new_params, new_slots, key, t + 1.0), (loss, bad)

            (params, slots, key, t), (losses, bads) = jax.lax.scan(
                body, (params, slots, rng_key, t), (list(inputs), list(labels)))
            return params, slots, losses, key, t, bads

        # Persistent-cache mode: jax.export cannot serialize typed PRNG
        # key avals, so when the compile cache is on the step program
        # takes/returns RAW key data (uint32) and wraps/unwraps at the
        # program boundary — numerics identical, program exportable.
        self._raw_key = _cc.enabled()
        if self._raw_key:
            base_pure, base_scan = pure, pure_scan

            def pure(params, slots, buffers, key_data, lr, t, inputs, labels):
                new_params, new_slots, loss, carry, t1, bad = base_pure(
                    params, slots, buffers,
                    jax.random.wrap_key_data(key_data), lr, t, inputs, labels)
                return (new_params, new_slots, loss,
                        jax.random.key_data(carry), t1, bad)

            def pure_scan(params, slots, buffers, key_data, lr, t,
                          inputs, labels):
                new_params, new_slots, losses, carry, t1, bads = base_scan(
                    params, slots, buffers,
                    jax.random.wrap_key_data(key_data), lr, t, inputs, labels)
                return (new_params, new_slots, losses,
                        jax.random.key_data(carry), t1, bads)

        donate = (0, 1, 3, 5) if self._donate else ()
        self._donate_argnums = donate
        self._jitted = jax.jit(pure, donate_argnums=donate)
        self._jitted_scan = jax.jit(pure_scan, donate_argnums=donate)
        self._key = rnd.default_generator().next_key()
        if self._raw_key:
            self._key = jax.random.key_data(self._key)
        self._t_arr = jnp.asarray(float(self.optimizer._step_count + 1),
                                  jnp.float32)
        self._lr_val = None
        self._lr_arr = None
        if _mem._ENABLED:
            self._tag_state()

    def _tag_state(self):
        """(Re-)tag the loop state for the live-buffer census. Called after
        build AND after every commit: the jit call donates the old param /
        slot / step-state buffers, so their tags die with them and the
        replacement arrays must be claimed again."""
        _mem.tag("params", [t._value for t in self._ptensors],
                 origin="TrainStep")
        _mem.tag("opt_slots", self._slots, origin="TrainStep")
        _mem.tag("step_state", [self._key, self._t_arr], origin="TrainStep")
        _mem.tag("model_buffers", [t._value for t in self._btensors],
                 origin="TrainStep")

    def _prepare(self, batch):
        """Shared prep for __call__/run: param/buffer arrays, model-input vs
        label split, lr-array cache refresh. Returns the batch split plus a
        `novel` flag — True when this batch signature has not been
        dispatched before, i.e. the jitted call ahead pays trace+compile
        (the timeline attributes it to `trace_compile`, not compute)."""
        if self._jitted is None:
            with _obs.phase("build"):
                self._build()
        params = [t._value for t in self._ptensors]
        buffers = [t._value for t in self._btensors]
        with _obs.phase("h2d"):
            arrs = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                    for b in batch]
        if _mem._ENABLED:
            _mem.tag("activations", arrs, origin="TrainStep.batch")
        n_mi = self._n_model_inputs
        if n_mi is None:
            n_mi = len(arrs) if len(arrs) <= 1 else len(arrs) - 1
        lr_val = self.optimizer.get_lr()
        if lr_val != self._lr_val:
            self._lr_val = lr_val
            self._lr_arr = jnp.asarray(lr_val, jnp.float32)
        novel, sig = False, None
        if _monitor._ENABLED or _obs._TL_ENABLED or _cc.enabled():
            # retrace accounting: the jitted step recompiles for every novel
            # batch signature — the dominant TPU perf hazard. The signature
            # that caused each retrace is logged for diagnosis (and the
            # timeline books the compile under trace_compile). The ledger
            # also keys the persistent-cache callables per signature.
            sig = _monitor.arg_signature(arrs)
            novel = self._ledger.note(sig)
        return params, buffers, arrs[:n_mi], arrs[n_mi:], novel, sig

    def __call__(self, *batch):
        """batch: input tensors consumed by model.forward; loss_fn receives the
        model output(s) — close labels into loss_fn or pass them as model inputs.
        """
        with _obs.step_record():
            params, buffers, inputs, labels, novel, sig = self._prepare(batch)
            _mon = _monitor._ENABLED
            if _mon:
                _t0 = _time.time()
            _tl = _obs._TL_ENABLED
            with _exe.booking("train_step") as bk:
                call = self._jitted
                if sig is not None:
                    cached = self._ledger.get(sig)
                    if cached is not None:
                        call = cached
                    elif novel:
                        if _cc.enabled():
                            # persistent-cache build step: a prior
                            # process's serialized executable (zero
                            # compiles here), or export+persist ours
                            args = (params, self._slots, buffers,
                                    self._key, self._lr_arr, self._t_arr,
                                    inputs, labels)
                            call, source = _exe.acquire(
                                "train_step", self._jitted, args,
                                donate=self._donate_argnums,
                                label="TrainStep")
                            self._ledger.put(sig, call)
                            if source == "fresh":
                                bk.compiled()
                        else:
                            bk.compiled()
                # OOM forensics drill site (`mem.alloc`) + the
                # RESOURCE_EXHAUSTED dump on the way out of a failure
                with _exe.dispatch_guard(
                        "TrainStep",
                        report=lambda: _obs.executable_memory(
                            self._jitted.lower(
                                params, self._slots, buffers, self._key,
                                self._lr_arr, self._t_arr, inputs,
                                labels).compile())):
                    new_params, self._slots, loss, self._key, self._t_arr, \
                        bad = call(params, self._slots, buffers,
                                   self._key, self._lr_arr,
                                   self._t_arr, inputs, labels)
                if _tl:
                    # fence: on an async backend the dispatch above returns
                    # before the chip finishes; without this the device time
                    # would leak into whatever phase syncs next
                    jax.block_until_ready(loss)
            # commit ALL state before any debug raise: the old param buffers
            # were DONATED to the jit call, so bailing out early would leave
            # every tensor pointing at a deleted buffer (and slots/step_count
            # desynced)
            for tns, v in zip(self._ptensors, new_params):
                tns._value = v
            self.optimizer._step_count += 1
            if _mem._ENABLED:
                self._tag_state()
            if _mon:
                _monitor.count("jit.train_step.steps")
                _monitor.observe("jit.train_step.dur", _time.time() - _t0)
            raise_nonfinite(bad, self._pnames, "jitted train step")
            return Tensor(loss)

    def cost_analysis(self, *batch):
        """XLA's own cost estimate for THIS step executable at `batch`'s
        signature: {"flops", "bytes_accessed", ...} via AOT
        lower().compile().cost_analysis() (obs/cost.py). The compile hits
        the same cache as __call__ for an already-dispatched signature.
        bench.py uses it to report *attributed* MFU — the compiler-counted
        FLOPs over measured step time — next to the formula-derived one."""
        params, buffers, inputs, labels, _, _sig = self._prepare(batch)
        lowered = self._jitted.lower(params, self._slots, buffers, self._key,
                                     self._lr_arr, self._t_arr, inputs,
                                     labels)
        return _obs.executable_cost(lowered.compile())

    def memory_report(self, *batch):
        """XLA's own memory breakdown for THIS step executable at `batch`'s
        signature: {"argument_bytes", "output_bytes", "temp_bytes",
        "alias_bytes", "generated_code_bytes", "peak_bytes"} via AOT
        lower().compile().memory_analysis() (obs/memory.py). temp_bytes is
        the number OOM forensics cares about — the scratch HBM the step
        needs ON TOP of the live buffers the census can see."""
        params, buffers, inputs, labels, _, _sig = self._prepare(batch)
        lowered = self._jitted.lower(params, self._slots, buffers, self._key,
                                     self._lr_arr, self._t_arr, inputs,
                                     labels)
        return _obs.executable_memory(lowered.compile())

    # ---- full loop-state capture (guard plane: preemption-safe resume) ----
    def named_param_arrays(self):
        """name -> device array for every trainable param (desync
        fingerprints; no copy)."""
        if self._jitted is None:
            self._build()
        return {n: t._value for n, t in zip(self._pnames, self._ptensors)}

    def state_dict(self):
        """Host-side copy of the FULL loop state: params, optimizer slots,
        the in-program rng carry key and step counter. `set_state_dict` of
        this dict reproduces the uninterrupted training stream
        bit-identically — the carry key is the exact key the next step
        would have split, not a reseeded approximation."""
        if self._jitted is None:
            self._build()
        import numpy as np_
        return {
            "kind": "train_step",
            "params": {n: np_.asarray(t._value)
                       for n, t in zip(self._pnames, self._ptensors)},
            "slots": [{k: np_.asarray(v) for k, v in s.items()}
                      for s in self._slots],
            "rng_key": np_.asarray(self._key if self._raw_key
                                   else jax.random.key_data(self._key)),
            "t": np_.asarray(self._t_arr),
            "step_count": int(self.optimizer._step_count),
        }

    def set_state_dict(self, sd):
        if self._jitted is None:
            self._build()
        params = sd["params"]
        for n, t in zip(self._pnames, self._ptensors):
            if n in params:
                t._value = jnp.asarray(params[n])
        self._slots = [{k: jnp.asarray(v) for k, v in s.items()}
                       for s in sd["slots"]]
        key_arr = jnp.asarray(sd["rng_key"])
        self._key = key_arr if self._raw_key \
            else jax.random.wrap_key_data(key_arr)
        self._t_arr = jnp.asarray(sd["t"], jnp.float32)
        self.optimizer._step_count = int(sd["step_count"])
        self._lr_val = None  # force the lr-array cache to refresh
        if _mem._ENABLED:
            self._tag_state()

    def run(self, *batch):
        """Device-side multi-step loop: every tensor in `batch` is stacked
        along a leading n_steps axis ([n, ...] per step-shape [...]); runs
        all n optimizer steps in one executable and returns the [n] loss
        history as a Tensor. One host dispatch + one sync per span instead
        of per step — the eager/tunnel dispatch tax disappears.
        """
        params, buffers, inputs, labels, _novel, _sig = self._prepare(batch)
        n_steps = int(inputs[0].shape[0]) if inputs else int(labels[0].shape[0])
        new_params, self._slots, losses, self._key, self._t_arr, bads = \
            self._jitted_scan(params, self._slots, buffers, self._key,
                              self._lr_arr, self._t_arr, inputs, labels)
        # commit before the debug raise (donated buffers — see __call__)
        for tns, v in zip(self._ptensors, new_params):
            tns._value = v
        self.optimizer._step_count += n_steps
        if _mem._ENABLED:
            self._tag_state()
        if _monitor._ENABLED:
            _monitor.count("jit.train_step.steps", n_steps)
        raise_nonfinite(bads, self._pnames, "jitted train step")
        return Tensor(losses)
