"""@to_static: capture a Layer/function into ONE compiled XLA program.

Reference parity: `python/paddle/fluid/dygraph/jit.py:163` (declarative) +
`dygraph_to_static/program_translator.py:775`. Like the reference, the
captured function is first AST-rewritten (paddle_tpu.jit.dy2static — the
ifelse/loop transformer equivalents) so Python `if`/`while`/`for` over
tensors lower to `lax.cond`/`lax.while_loop` automatically; explicit
`paddle_tpu.static.nn.cond/while_loop` remain available for full control.

Differentiability: the whole compiled program is recorded as ONE tape node
(vjp through `jax.jit`), so `loss.backward()` works across the static
boundary exactly like `run_program_op`'s grad in the reference
(`operators/run_program_op.cc`).
"""
from __future__ import annotations

import functools
import weakref

import jax

from .. import analysis as _analysis
from .. import monitor as _monitor
from ..core import compile_cache as _cc
from ..core import executable as _exe
from ..core import random as rnd
from ..core.tensor import Tensor
from ..ops._dispatch import run_op
from .functional import functional_call, split_state
from .input_spec import InputSpec  # noqa: F401  (re-export)


class StaticFunction:
    def __init__(self, function, layer=None, input_spec=None):
        try:
            from .dy2static import ast_transform
            self._function = ast_transform(function)
        except Exception:  # source unavailable / exotic callable: trace as-is
            self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_cache = {}
        # executable substrate: only a NOVEL signature is a recompile —
        # alternating between two known shapes (e.g. the serving engine
        # cycling batch buckets) replays jax.jit's cache and must not
        # count as retraces. The ledger also caches persistent-cache
        # deserialized executables per signature, and its `current_sig`
        # is the signature the published Program was built for.
        self._ledger = _exe.ExecutableLedger("to_static")
        try:
            functools.update_wrapper(self, function)
        except Exception:
            pass

    @property
    def layer(self):
        return self._layer

    def release(self) -> None:
        """Drop every cached executable. The `pure` closures in
        `_jit_cache` reference `self` through jax's C-level function
        wrappers, which the cycle collector cannot traverse — an owner
        that wants `self._layer`'s weights freed must break the cycle
        explicitly (e.g. a serving engine on `stop()`)."""
        self._jit_cache.clear()
        self._ledger.clear()

    def _get_pure(self, training, pnames, bnames, static_kwargs):
        key = ("pure", training, tuple(pnames), tuple(bnames),
               tuple(sorted(static_kwargs.items())))
        pure = self._jit_cache.get(key)
        if pure is None:
            # Capture self WEAKLY: jax's C-level jit machinery keeps a
            # reference to `pure` in a process-global cache, so a strong
            # `layer`/`func` cell here would pin the whole model long
            # after the StaticFunction is dropped. The weakref is always
            # live during a call — the caller IS the StaticFunction.
            self_ref = weakref.ref(self)
            kw = dict(static_kwargs)

            def pure(param_arrays, buffer_arrays, rng_key, input_arrays):
                sf = self_ref()
                if sf is None:  # pragma: no cover - defensive
                    raise RuntimeError("StaticFunction was released")
                layer, func = sf._layer, sf._function
                rnd.push_trace_key(rng_key)
                swapped = layer is not None and isinstance(
                    layer.__dict__.get("forward"), StaticFunction)
                if swapped:  # un-hook ourselves so tracing hits the original forward
                    saved_fwd = layer.__dict__["forward"]
                    layer.__dict__["forward"] = func
                try:
                    if layer is not None:
                        return functional_call(layer, pnames, param_arrays, bnames,
                                               buffer_arrays, *input_arrays, **kw)
                    wrapped = [Tensor(a) for a in input_arrays]
                    out = func(*wrapped, **kw)
                    return jax.tree_util.tree_map(
                        lambda t: t._value if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda x: isinstance(x, Tensor))
                finally:
                    rnd.pop_trace_key()
                    if swapped:
                        layer.__dict__["forward"] = saved_fwd

            self._jit_cache[key] = pure
        return pure

    def _get_jitted(self, training, pnames, bnames, static_kwargs,
                    raw_key=False):
        key = ("jit", training, tuple(pnames), tuple(bnames),
               tuple(sorted(static_kwargs.items())), raw_key)
        jitted = self._jit_cache.get(key)
        if jitted is None:
            if _monitor._ENABLED:
                _monitor.count("jit.to_static.cache_miss")
            pure = self._get_pure(training, pnames, bnames, static_kwargs)
            if raw_key:
                # persistent-cache mode: jax.export cannot serialize
                # typed PRNG key avals, so the exported program takes RAW
                # key data and wraps at the boundary (same adapter as
                # TrainStep._build)
                base = pure

                def pure(param_arrays, buffer_arrays, key_data,
                         input_arrays):
                    return base(param_arrays, buffer_arrays,
                                jax.random.wrap_key_data(key_data),
                                input_arrays)

            jitted = jax.jit(pure)
            self._jit_cache[key] = jitted
        return jitted

    def _get_fwd_vjp(self, training, pnames, bnames, static_kwargs, n_p):
        """jit'd (outs, vjp) of the pure forward with the rng key and
        buffer arrays as ARGUMENTS. The earlier design closed the per-call
        rng key into the run_op fn, which made every call miss the global
        vjp cache (`_fn_key` correctly refuses to value-key arrays) and
        dropped backward to an unjitted transposed-jaxpr walk — measured
        78 ms/step LeNet vs 44 eager. With key/buffers as traced args the
        whole fwd+vjp pair is ONE cached executable each way."""
        key = ("fwd_vjp", training, tuple(pnames), tuple(bnames),
               tuple(sorted(static_kwargs.items())), n_p)
        f = self._jit_cache.get(key)
        if f is None:
            if _monitor._ENABLED:
                _monitor.count("jit.to_static.cache_miss")
            pure = self._get_pure(training, pnames, bnames, static_kwargs)

            def fwd_vjp(diff, barrs, rkey):
                def g(*d):
                    return pure(list(d[:n_p]), barrs, rkey, list(d[n_p:]))
                return jax.vjp(g, *diff)

            f = jax.jit(fwd_vjp)
            self._jit_cache[key] = f
        return f

    def __call__(self, *args, **kwargs):
        from ..core import autograd as _ag
        layer = self._layer
        input_tensors = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
        if any(isinstance(v, Tensor) for v in kwargs.values()):
            raise ValueError("to_static: pass Tensor arguments positionally")
        try:
            hash(tuple(sorted(kwargs.items())))
            static_kwargs = kwargs
        except TypeError:
            raise ValueError("to_static kwargs must be hashable (static) values")

        if layer is not None:
            trainable, frozen = split_state(layer)
            pnames, bnames = list(trainable), list(frozen)
            ptensors = [trainable[n] for n in pnames]
            barrs = [frozen[n]._value for n in bnames]
            # composite mode flag: sublayer train/eval toggles re-key the
            # trace caches (a capture traced with dropout active must not
            # replay after model.dropout.eval())
            training = tuple(l.training for l in
                             layer.sublayers(include_self=True))
        else:
            pnames, bnames, ptensors, barrs = [], [], [], []
            training = True

        key = rnd.default_generator().next_key()
        n_p = len(ptensors)
        diff_inputs = ptensors + input_tensors
        arrays = [t._value for t in diff_inputs]
        # persistent-cache mode rides the raw-key-data program variant
        raw = _cc.enabled()
        karg = jax.random.key_data(key) if raw else key

        # publish this capture as the default program (ProgramDesc role):
        # introspection/pruning lower lazily from the same traced callable.
        # Rebuilt only when the input signature changes (zero steady-state
        # cost on the hot path).
        sig = tuple((t._value.shape, str(t._value.dtype)) for t in diff_inputs)
        # a NOVEL signature on a to_static capture = retrace: the whole
        # program recompiles for the new shapes/dtypes. A previously-seen
        # signature hits jax.jit's executable cache and is free — only
        # the Program rebuild below runs.
        novel = self._ledger.note(sig, detail=[f"{s}:{d}" for s, d in sig])
        if self._ledger.current_sig != sig:
            if _analysis._ENABLED:
                # trace-time tpu-lint: novel-signature block only, so the
                # steady-state call path never reaches this check
                _analysis.lint_traced(self._function, "to_static")
            jitted = self._get_jitted(training, pnames, bnames,
                                      static_kwargs, raw)

            def fn(*arrs, _jit=jitted, _b=list(barrs), _k=karg, _np=n_p):
                return _jit(list(arrs[:_np]), _b, _k, list(arrs[_np:]))

            from ..static.program import Program, _set_default_program
            specs = [jax.ShapeDtypeStruct(t._value.shape, t._value.dtype)
                     for t in diff_inputs]
            self._last_program = Program(fn, specs, name=getattr(
                self._function, "__name__", "main"))
            self._ledger.current_sig = sig
            _set_default_program(self._last_program)

        import time as _time
        _t0 = _time.time()
        record = (_ag.is_grad_enabled()
                  and any(not t.stop_gradient for t in diff_inputs)
                  and not any(isinstance(a, jax.core.Tracer) for a in arrays))
        if not record:
            jitted = self._get_jitted(training, pnames, bnames,
                                      static_kwargs, raw)
            csig = (sig, training, tuple(sorted(static_kwargs.items())), raw)
            with _exe.booking("to_static") as bk:
                call = self._ledger.get(csig)
                if call is None:
                    call = jitted
                    if raw:
                        call, source = _exe.acquire(
                            "to_static", jitted,
                            (arrays[:n_p], barrs, karg, arrays[n_p:]),
                            label=getattr(self._function, "__name__",
                                          "to_static"))
                        self._ledger.put(csig, call)
                        if novel and source == "fresh":
                            bk.compiled()
                    elif novel:
                        bk.compiled()
                elif novel:
                    bk.compiled()
                out = call(arrays[:n_p], barrs, karg, arrays[n_p:])
        else:
            with _exe.booking("to_static") as bk:
                if novel:
                    bk.compiled()
                fwd_vjp = self._get_fwd_vjp(training, pnames, bnames,
                                            static_kwargs, n_p)
                out, raw_vjp = fwd_vjp(arrays, barrs, key)
        # arbitrary output pytrees (e.g. RNN layers return (out, (h, c))):
        # the tape stores flat leaf tensors; the vjp wrapper unflattens the
        # flat cotangents back to the traced structure
        leaves, treedef = jax.tree_util.tree_flatten(out)
        # tape convention: bare cotangent for single output, flat tuple for
        # >1. A 1-TUPLE output is NOT native (the vjp expects (c,), the
        # tape would pass a bare array) — keep its treedef for unflatten.
        flat_native = (treedef == jax.tree_util.tree_structure(0)
                       or (len(leaves) > 1 and treedef ==
                           jax.tree_util.tree_structure(tuple(leaves))))
        outs_list = [Tensor(o) for o in leaves]
        from ..ops import _dispatch as _dsp
        from ..core import flags as _flags
        if _flags.flag("check_nan_inf") and not any(
                isinstance(o, jax.core.Tracer) for o in leaves):
            _dsp._check_nan_inf("static_program", tuple(leaves))
        if _dsp._PROFILE_HOOK is not None:
            import time as _time
            _dsp._PROFILE_HOOK("static_program", _t0, _time.time())
        if _monitor._ENABLED:
            import time as _time
            _monitor.count("jit.to_static.calls")
            _monitor.observe("jit.to_static.dur", _time.time() - _t0)
        if record:
            _ag.record_node(
                _ag._JitVJP(raw_vjp,
                            treedef=None if flat_native else treedef),
                diff_inputs, outs_list, "static_program")
        return jax.tree_util.tree_unflatten(
            treedef, [t for t in outs_list])

    def program(self, *args):
        """The Program captured by the most recent call (lazy-lowered);
        with args, captures a fresh one for those input shapes."""
        if args:
            self(*args)
        prog = getattr(self, "_last_program", None)
        if prog is None:
            raise RuntimeError("call the @to_static function once (or pass "
                               "example args) to capture its program")
        return prog


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Decorator/wrapper. Accepts a Layer, a Layer's bound forward, or a pure
    function of Tensors."""

    def decorate(obj):
        from ..nn.layer.layers import Layer
        if isinstance(obj, Layer):
            static = StaticFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = static
            return obj
        if hasattr(obj, "__self__") and isinstance(obj.__self__, Layer):
            return StaticFunction(obj.__func__.__get__(obj.__self__),
                                  layer=obj.__self__, input_spec=input_spec)
        return StaticFunction(obj, layer=None, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn
