"""@to_static: capture a Layer/function into ONE compiled XLA program.

Reference parity: `python/paddle/fluid/dygraph/jit.py:163` (declarative) +
`dygraph_to_static/program_translator.py:775`. The reference rewrites Python
AST into ProgramDesc ops; on TPU we let JAX trace the same Python (data-
dependent control flow must use paddle_tpu.static.nn.cond/while_loop, the
lax.cond/while analogue — same restriction the reference's AST transforms
lift, here made explicit).

Differentiability: the whole compiled program is recorded as ONE tape node
(vjp through `jax.jit`), so `loss.backward()` works across the static
boundary exactly like `run_program_op`'s grad in the reference
(`operators/run_program_op.cc`).
"""
from __future__ import annotations

import functools

import jax

from ..core import random as rnd
from ..core.tensor import Tensor
from ..ops._dispatch import run_op
from .functional import functional_call, split_state
from .input_spec import InputSpec  # noqa: F401  (re-export)


class StaticFunction:
    def __init__(self, function, layer=None, input_spec=None):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._jit_cache = {}
        try:
            functools.update_wrapper(self, function)
        except Exception:
            pass

    @property
    def layer(self):
        return self._layer

    def _get_jitted(self, training, pnames, bnames, static_kwargs):
        key = (training, tuple(pnames), tuple(bnames),
               tuple(sorted(static_kwargs.items())))
        jitted = self._jit_cache.get(key)
        if jitted is None:
            layer, func = self._layer, self._function
            kw = dict(static_kwargs)

            def pure(param_arrays, buffer_arrays, rng_key, input_arrays):
                rnd.push_trace_key(rng_key)
                swapped = layer is not None and isinstance(
                    layer.__dict__.get("forward"), StaticFunction)
                if swapped:  # un-hook ourselves so tracing hits the original forward
                    saved_fwd = layer.__dict__["forward"]
                    layer.__dict__["forward"] = func
                try:
                    if layer is not None:
                        return functional_call(layer, pnames, param_arrays, bnames,
                                               buffer_arrays, *input_arrays, **kw)
                    wrapped = [Tensor(a) for a in input_arrays]
                    out = func(*wrapped, **kw)
                    return jax.tree_util.tree_map(
                        lambda t: t._value if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda x: isinstance(x, Tensor))
                finally:
                    rnd.pop_trace_key()
                    if swapped:
                        layer.__dict__["forward"] = saved_fwd

            jitted = jax.jit(pure)
            self._jit_cache[key] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        layer = self._layer
        input_tensors = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
        if any(isinstance(v, Tensor) for v in kwargs.values()):
            raise ValueError("to_static: pass Tensor arguments positionally")
        try:
            hash(tuple(sorted(kwargs.items())))
            static_kwargs = kwargs
        except TypeError:
            raise ValueError("to_static kwargs must be hashable (static) values")

        if layer is not None:
            trainable, frozen = split_state(layer)
            pnames, bnames = list(trainable), list(frozen)
            ptensors = [trainable[n] for n in pnames]
            barrs = [frozen[n]._value for n in bnames]
            training = layer.training
        else:
            pnames, bnames, ptensors, barrs = [], [], [], []
            training = True

        jitted = self._get_jitted(training, pnames, bnames, static_kwargs)
        key = rnd.default_generator().next_key()
        n_p = len(ptensors)
        diff_inputs = ptensors + input_tensors

        def fn(*arrays):
            return jitted(list(arrays[:n_p]), barrs, key, list(arrays[n_p:]))

        return run_op(fn, diff_inputs, "static_program")


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """Decorator/wrapper. Accepts a Layer, a Layer's bound forward, or a pure
    function of Tensors."""

    def decorate(obj):
        from ..nn.layer.layers import Layer
        if isinstance(obj, Layer):
            static = StaticFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = static
            return obj
        if hasattr(obj, "__self__") and isinstance(obj.__self__, Layer):
            return StaticFunction(obj.__func__.__get__(obj.__self__),
                                  layer=obj.__self__, input_spec=input_spec)
        return StaticFunction(obj, layer=None, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn
