"""jit.save / jit.load — serialize a traced program + params.

Reference parity: `paddle.jit.save/load` → TranslatedLayer
(`python/paddle/fluid/dygraph/io.py`): the reference serializes a pruned
ProgramDesc + params. TPU-native: we serialize the traced XLA program as a
portable StableHLO artifact via `jax.export` (`{path}.pdmodel`) plus an npz
of the state dict (`{path}.pdiparams`). Loading needs no Python model code —
true deploy parity with the reference's save_inference_model flow.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as _jax_export

from ..core.tensor import Tensor
from .functional import split_state
from .input_spec import InputSpec


def save(layer, path, input_spec=None, **configs):
    from ..nn.layer.layers import Layer
    from .to_static import StaticFunction

    if isinstance(layer, Layer):
        fwd = layer.__dict__.get("forward")
        fn = fwd._function if isinstance(fwd, StaticFunction) else layer.forward
        model = layer
    elif isinstance(layer, StaticFunction):
        model = layer.layer
        fn = layer._function
    else:
        raise TypeError("jit.save expects a Layer or @to_static function")

    if input_spec is None:
        raise ValueError("jit.save requires input_spec on the TPU build "
                         "(shapes must be static for XLA export)")
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in input_spec]

    was_training = model.training
    model.eval()
    trainable, frozen = split_state(model)
    pnames, bnames = list(trainable), list(frozen)
    parrs = [trainable[n]._value for n in pnames]
    barrs = [frozen[n]._value for n in bnames]

    # Inference precision is decided at export, the TPU-native analog of the
    # reference predictor rebuilding a TRT/mkldnn engine per precision mode
    # (paddle_analysis_config.h precision_mode): params and float inputs are
    # cast so XLA keeps every conv/matmul on the bf16 MXU path.
    precision = configs.pop("precision", None)
    if precision not in (None, "float32", "bfloat16", "float16", "half",
                         "bf16", "fp16", "int8", "int8_weight_only"):
        raise ValueError(f"unsupported save precision {precision!r}; "
                         "use 'float32', 'bfloat16' or 'int8' (weight-only)")
    quantized_names = []
    orig_parrs = list(parrs)  # pre-cast values: int8 quantizes from fp32
    if precision in ("bfloat16", "float16", "half", "bf16", "fp16",
                     "int8", "int8_weight_only"):
        cast = jnp.bfloat16  # fp16 maps to bf16 on TPU (same MXU path)
        parrs = [a.astype(cast) if jnp.issubdtype(a.dtype, jnp.floating) else a
                 for a in parrs]
        barrs = [a.astype(cast) if jnp.issubdtype(a.dtype, jnp.floating) else a
                 for a in barrs]
        specs = [InputSpec(s.shape, "bfloat16" if np.issubdtype(np.dtype(s.dtype),
                                                                np.floating) else s.dtype,
                           getattr(s, "name", None))
                 for s in specs]
    qmask = [False] * len(parrs)
    if precision in ("int8", "int8_weight_only"):
        # weight-only int8: matmul/conv weights become int8 ARGUMENTS of the
        # exported program with per-channel scales appended to the buffer
        # list; dequant to bf16 happens INSIDE the trace, which XLA fuses
        # into the consumer — int8 is what sits in HBM. TPU-native stand-in
        # for the reference's TRT/mkldnn int8 engines
        # (inference/api/mkldnn_quantizer.cc role).
        from ..quantization import channel_quant
        scales = []
        new_parrs = []
        for i, (n, a, orig) in enumerate(zip(pnames, parrs, orig_parrs)):
            if a.ndim >= 2 and jnp.issubdtype(a.dtype, jnp.floating):
                # quantize the ORIGINAL (pre-bf16-cast) values: double
                # rounding through bf16 would waste int8 grid accuracy
                q, scale = channel_quant(np.asarray(orig, dtype=np.float32))
                new_parrs.append(jnp.asarray(q))
                scales.append(jnp.asarray(scale))
                qmask[i] = True
                quantized_names.append(n)
            else:
                new_parrs.append(a)
        parrs = new_parrs
        n_model_buffers = len(barrs)
        barrs = list(barrs) + scales
        bnames = bnames + [f"__scale__{n}" for n in quantized_names]

    from .functional import functional_call

    if quantized_names:
        model_bnames = bnames[:n_model_buffers]

        def pure(params, buffers, *inputs):
            real_b = list(buffers[:n_model_buffers])
            sc = list(buffers[n_model_buffers:])
            ps, si = [], 0
            for flag, p in zip(qmask, params):
                if flag:
                    # dequant in-trace: XLA fuses this into the matmul/conv
                    # reading the weight, so HBM keeps the int8 bytes
                    ps.append(p.astype(jnp.bfloat16)
                              * sc[si].astype(jnp.bfloat16))
                    si += 1
                else:
                    ps.append(p)
            return functional_call(model, pnames, ps, model_bnames, real_b,
                                   *inputs)
    else:
        def pure(params, buffers, *inputs):
            out = functional_call(model, pnames, params, bnames, buffers,
                                  *inputs)
            return out

    arg_specs = (
        [jax.ShapeDtypeStruct(tuple(1 if d == -1 else d for d in s.shape), s.dtype)
         for s in specs])
    exported = _jax_export.export(jax.jit(pure))(
        [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in parrs],
        [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in barrs],
        *arg_specs)
    blob = exported.serialize()

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    np.savez(path + ".pdiparams",
             **{f"p::{n}": np.asarray(a) for n, a in zip(pnames, parrs)},
             **{f"b::{n}": np.asarray(a) for n, a in zip(bnames, barrs)})
    from ..framework.version import FRAMEWORK_VERSION, GLOBAL_OP_VERSION_REGISTRY
    meta = {"input_specs": [{"shape": list(s.shape), "dtype": np.dtype(s.dtype).name}
                            for s in specs],
            "param_names": pnames, "buffer_names": bnames,
            # npz stores bf16 as raw void ('|V2'); dtypes let load re-view
            "param_dtypes": [np.dtype(a.dtype).name for a in parrs],
            "buffer_dtypes": [np.dtype(a.dtype).name for a in barrs],
            # weight-only int8 artifacts list their quantized params;
            # "precision" distinguishes an int8 EXPORT with zero
            # quantizable tensors from a non-int8 artifact
            "quantized": quantized_names,
            "precision": ("int8" if precision in ("int8", "int8_weight_only")
                          else (precision or "float32")),
            # version stamping (framework/version.cc + op_version_registry)
            "framework_version": FRAMEWORK_VERSION,
            "op_versions": GLOBAL_OP_VERSION_REGISTRY.snapshot()}
    with open(path + ".pdmodel.json", "w") as f:
        json.dump(meta, f)
    if was_training:
        model.train()
    return path


class TranslatedLayer:
    """Loaded inference program: callable like a Layer (forward only)."""

    def __init__(self, exported, params, buffers, meta):
        self._exported = exported
        self._params = params
        self._buffers = buffers
        self._meta = meta
        self.training = False
        # Exported.call rebuilds its calling convention per invocation;
        # jitting it once puts repeat predictions on XLA's fast C++
        # dispatch path (the predictor hot loop)
        self._jitted_call = jax.jit(
            lambda params, buffers, *a: exported.call(params, buffers, *a))

    def __call__(self, *args):
        arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._jitted_call(self._params, self._buffers, *arrs)
        if isinstance(out, (list, tuple)):
            return [Tensor(o) for o in out]
        return Tensor(out)

    forward = __call__

    def eval(self):
        return self

    def state_dict(self):
        names = self._meta["param_names"] + self._meta["buffer_names"]
        vals = list(self._params) + list(self._buffers)
        return {n: Tensor(v) for n, v in zip(names, vals)}


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = _jax_export.deserialize(f.read())
    with open(path + ".pdmodel.json") as f:
        meta = json.load(f)
    from ..framework.version import (GLOBAL_OP_VERSION_REGISTRY,
                                     is_compatible)
    if "framework_version" in meta and not is_compatible(meta["framework_version"]):
        raise RuntimeError(
            f"artifact written by incompatible version "
            f"{meta['framework_version']}")
    for msg in GLOBAL_OP_VERSION_REGISTRY.incompatibilities(
            meta.get("op_versions", {})):
        import warnings
        warnings.warn(f"op semantics changed since save: {msg}")
    data = np.load(path + ".pdiparams.npz")

    def _blob(key, dtype_name):
        a = data[key]
        if dtype_name and a.dtype != np.dtype(dtype_name):
            a = a.view(np.dtype(dtype_name))
        return jnp.asarray(a)

    pdt = meta.get("param_dtypes") or [None] * len(meta["param_names"])
    bdt = meta.get("buffer_dtypes") or [None] * len(meta["buffer_names"])
    params = [_blob(f"p::{n}", d) for n, d in zip(meta["param_names"], pdt)]
    buffers = [_blob(f"b::{n}", d) for n, d in zip(meta["buffer_names"], bdt)]
    return TranslatedLayer(exported, params, buffers, meta)
