"""paddle.jit parity: to_static capture, jitted train step, save/load."""
from .input_spec import InputSpec  # noqa: F401
from .to_static import StaticFunction, declarative, not_to_static, to_static  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from .save_load import TranslatedLayer, load, save  # noqa: F401

# ---- legacy compat surface -------------------------------------------------
from .to_static import to_static as _ts


class ProgramTranslator:
    """dygraph_to_static ProgramTranslator compat: the global toggle for
    to_static conversion (`program_translator.py` singleton)."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        type(self)._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return type(self)._enabled


def enable_to_static(flag: bool = True):
    ProgramTranslator.get_instance().enable(flag)


class TracedLayer:
    """dygraph.TracedLayer compat over StaticFunction: trace(layer, inputs)
    returns (outputs, traced) where traced(*) replays the captured program
    and save_inference_model exports it (jit.save)."""

    def __init__(self, static_fn, layer):
        self._fn = static_fn
        self._layer = layer

    @staticmethod
    def trace(layer, inputs):
        from .to_static import StaticFunction
        sf = StaticFunction(type(layer).forward.__get__(layer), layer=layer)
        outs = sf(*inputs)
        return outs, TracedLayer(sf, layer)

    def __call__(self, *inputs):
        return self._fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        from .input_spec import InputSpec
        from .save_load import save as _save
        prog = self._fn.program()
        specs = [InputSpec(list(s.shape), str(s.dtype))
                 for s in prog.in_specs[len(list(
                     self._layer.parameters())):]] if hasattr(
                         prog, "in_specs") else None
        _save(self._layer, path, input_spec=specs, **kw)


# verbosity/code-level knobs (dy2static debugging surface): stored and
# honored by dy2static's transform logging when enabled
_JIT_VERBOSITY = [0]
_JIT_CODE_LEVEL = [0]


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    _JIT_VERBOSITY[0] = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    _JIT_CODE_LEVEL[0] = int(level)
