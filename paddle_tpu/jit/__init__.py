"""paddle.jit parity: to_static capture, jitted train step, save/load."""
from .input_spec import InputSpec  # noqa: F401
from .to_static import StaticFunction, declarative, not_to_static, to_static  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from .save_load import TranslatedLayer, load, save  # noqa: F401
