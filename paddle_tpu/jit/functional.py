"""Functionalisation of Layers: run a Layer's forward with its parameters и
buffers temporarily bound to arbitrary arrays (jax tracers included).

This is the TPU-native replacement for the reference's dygraph-to-static
ProgramDescTracer (`imperative/jit/program_desc_tracer.cc`): instead of
re-recording ops into a ProgramDesc, we let JAX trace the same Python
forward with tracer-backed parameters.
"""
from __future__ import annotations

import collections
from contextlib import contextmanager
from typing import Dict, List, Tuple

from ..core.tensor import Parameter, Tensor


def split_state(layer) -> Tuple[Dict[str, Parameter], Dict[str, Tensor]]:
    """(trainable params, buffers+frozen params) by state_dict name."""
    trainable = collections.OrderedDict()
    frozen = collections.OrderedDict()
    for name, t in layer.state_dict().items():
        if isinstance(t, Parameter) and not t.stop_gradient:
            trainable[name] = t
        else:
            frozen[name] = t
    return trainable, frozen


@contextmanager
def bind_arrays(tensors: List[Tensor], arrays):
    """Temporarily swap each tensor's payload with the given arrays."""
    saved = [t._value for t in tensors]
    saved_nodes = [t._node for t in tensors]
    try:
        for t, a in zip(tensors, arrays):
            t._value = a
            t._node = None
        yield
    finally:
        for t, v, n in zip(tensors, saved, saved_nodes):
            t._value = v
            t._node = n


def functional_call(layer, param_names, param_arrays, buffer_names, buffer_arrays,
                    *args, **kwargs):
    """Run layer(*args) with named state bound to the provided arrays.

    args/kwargs may contain raw arrays (wrapped into Tensors) or Tensors.
    Returns raw array pytree (Tensor payloads unwrapped).
    """
    state = layer.state_dict()
    ptensors = [state[n] for n in param_names]
    btensors = [state[n] for n in buffer_names]

    def wrap(x):
        return Tensor(x) if not isinstance(x, Tensor) else x

    import jax
    wrapped_args = jax.tree_util.tree_map(
        wrap, list(args), is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))
    with bind_arrays(ptensors + btensors, list(param_arrays) + list(buffer_arrays)):
        out = layer(*wrapped_args, **kwargs)
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, out,
        is_leaf=lambda x: isinstance(x, Tensor))


def amp_functional_call(model, pnames, ps, bnames, buffers, inputs, amp_dtype):
    """functional_call under O1 autocast when amp_dtype is set.

    Casts floating params to amp_dtype AND enables the autocast state for
    the trace — white-list ops (matmul/conv) then cast fp32 activations
    down too; casting params alone would let one fp32 input promote the
    whole graph back to fp32. Shared by TrainStep and SPMDTrainStep.
    """
    if amp_dtype is None:
        return functional_call(model, pnames, ps, bnames, buffers, *inputs)
    import jax.numpy as jnp
    ps = [p.astype(amp_dtype)
          if jnp.issubdtype(p.dtype, jnp.floating) else p for p in ps]
    from ..amp.state import auto_cast
    with auto_cast(enable=True, dtype=amp_dtype):
        return functional_call(model, pnames, ps, bnames, buffers, *inputs)
