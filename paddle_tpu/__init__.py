"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas.

Public surface mirrors `paddle.*`: imperative Tensors with autograd,
`nn`/`optimizer`/`amp`/`jit`/`io`/`distributed` subpackages, static capture
via `jit.to_static` → XLA, and SPMD parallelism over `jax.sharding.Mesh`.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .core.tensor import Tensor, Parameter  # noqa: F401
from .core.dtype import (  # noqa: F401
    bool_ as bool,  # noqa: A001
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128, set_default_dtype, get_default_dtype, finfo, iinfo,
)
from .core.place import (  # noqa: F401
    CPUPlace, TPUPlace, CUDAPlace, Place, set_device, get_device, device_count,
    is_compiled_with_tpu,
)
from .core.flags import set_flags, get_flags  # noqa: F401
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .core.autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
from .core.lod import LoDTensor, create_lod_tensor  # noqa: F401
from .core.autograd import grad_fn as _grad_fn
from .core import enforce  # noqa: F401  (typed errors: paddle.enforce.errors)
from .core.enforce import errors  # noqa: F401

from . import ops  # noqa: F401  (binds Tensor methods)
from .ops import *  # noqa: F401,F403

# subpackages (populated progressively; import order matters: nn before optimizer)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import autograd  # noqa: F401
from . import metric  # noqa: F401
from . import linalg  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import device  # noqa: F401
from . import distribution  # noqa: F401
from . import sparse  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import models  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401  (dynamic-batching inference engine)
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401  (stats registry + trace spans plane)
from . import obs  # noqa: F401  (step timeline + flight recorder plane)
from . import analysis  # noqa: F401  (tpu-lint static-analysis plane)
from . import faults  # noqa: F401  (deterministic fault injection plane)
from . import guard  # noqa: F401  (training guard plane: resume/watchdog/rollback/desync)
from . import incubate  # noqa: F401
from . import quantization  # noqa: F401
from . import distributed  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from .hapi.dynamic_flops import flops  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .nn.layer.layers import Layer  # noqa: F401  (paddle.nn.Layer also reachable)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """paddle.grad parity (python/paddle/fluid/dygraph/base.py grad)."""
    gs = _grad_fn(outputs, inputs, grad_outputs, retain_graph, create_graph, allow_unused)
    # create_graph returns tape-linked Tensors; rewrapping would drop the node
    return [None if g is None else (g if isinstance(g, Tensor) else Tensor(g))
            for g in gs]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    from .ops.creation import to_tensor as _tt
    return _tt(data, dtype, place, stop_gradient)


def sync():
    """Explicit sync point for the lazy batching eager executor
    (FLAGS_lazy_eager): flush the calling thread's pending segment so
    every deferred op is dispatched and its outputs are materialized.
    A no-op when nothing is pending (including lazy mode off)."""
    from .ops import lazy as _lazy
    _lazy.flush_pending()


def disable_static(place=None):
    return None  # dynamic mode is the default and only eager mode


def enable_static():
    from . import static as _static
    _static._STATIC_MODE[0] = True


def in_dynamic_mode():
    from . import static as _static
    return not _static._STATIC_MODE[0]


def is_grad_enabled_():
    return is_grad_enabled()


def device_guard(*a, **kw):  # static-graph relic; no-op on TPU
    import contextlib
    return contextlib.nullcontext()


# ---- remaining top-level parity surface (reference paddle/__init__.py) ----
# paddle.dtype: the type OF dtype objects (isinstance(x.dtype, paddle.dtype))
import numpy as _np
dtype = _np.dtype
from .core.place import CUDAPinnedPlace, NPUPlace  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401
from .parallel.data_parallel import DataParallel  # noqa: F401

# CUDA rng-state aliases: the rng state is backend-agnostic here (one
# jax PRNG key chain), matching set/get_cuda_rng_state call sites
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def disable_signal_handler():
    """No-op: the reference installs C++ fatal-signal dumpers
    (`paddle/fluid/platform/init.cc` SignalHandle); python/XLA runtimes
    leave process signal handling to the host."""
    return None


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor print formatting (`python/paddle/tensor/to_string.py`):
    forwards to numpy's printoptions, which Tensor.__repr__ uses."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def check_shape(shape):
    """Validate a shape argument (fluid check_shape utility): ints or a
    1-D integer tensor, -1 allowed once for inferred dims."""
    vals = shape.tolist() if hasattr(shape, "tolist") else list(shape)
    n_infer = 0
    for v in vals:
        if not isinstance(v, (int,)) and not float(v).is_integer():
            raise TypeError(f"shape entries must be integers, got {v!r}")
        if int(v) == -1:
            n_infer += 1
    if n_infer > 1:
        raise ValueError("only one dimension may be -1")
    return True


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader (`python/paddle/batch.py`)."""
    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched
