"""paddle.metric parity: Metric base, Accuracy, Precision, Recall, Auc.

Reference parity: `python/paddle/metric/metrics.py`.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label.squeeze(-1)
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        correct = order == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0] if correct.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].sum()
            self.count[i] += num
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = (self.total / np.maximum(self.count, 1)).tolist()
        return accs[0] if len(self.topk) == 1 else accs

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(int).reshape(-1)
        labels = _np(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(int).reshape(-1)
        labels = _np(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        bins = np.minimum((preds * self.num_thresholds).astype(int), self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = tot_neg = auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * self._stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    ok = (order == lab[:, None]).any(-1).mean()
    from ..core.tensor import Tensor as T
    import jax.numpy as jnp
    return T(jnp.asarray(np.float32(ok)))
