"""paddle.profiler parity over the JAX/XLA profiler.

Reference parity: `python/paddle/profiler/profiler.py:224` (Profiler with
scheduler states CLOSED/READY/RECORD, `export_chrome_tracing`:128) and the
C++ host/device tracers (`platform/profiler/`). TPU device timeline comes
from the XLA profiler (TraceMe + device trace), written as a TensorBoard-
compatible trace that includes chrome-trace events — same artifact role as
`chrometracing_logger.cc`.
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name

    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._export_dir = None
        if on_trace_ready is not None:
            # export_chrome_tracing handlers configure the trace dir; apply
            # eagerly so start_trace targets the requested directory
            try:
                on_trace_ready(self)
            except Exception:
                pass
        self._active = False
        self.step_num = 0
        self._step_times = []
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        self._t0 = time.time()
        if not self._timer_only:
            self._export_dir = self._export_dir or "./profiler_log"
            os.makedirs(self._export_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._export_dir)
                self._active = True
            except Exception:
                self._active = False
        return self

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.time()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        self.step_num += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        ts = np.asarray(self._step_times[-10:])
        return f"avg step {ts.mean()*1000:.2f} ms (last {len(ts)})"

    def export(self, path, format="json"):
        pass  # chrome trace already exported by stop_trace

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        return self.step_info()


@contextlib.contextmanager
def RecordEvent(name, event_type=None):
    """Host-side instrumentation (TraceMe). Parity: `platform/profiler/event_tracing.h`."""
    with jax.profiler.TraceAnnotation(name):
        yield


def load_profiler_result(filename):
    raise NotImplementedError("load_profiler_result: use TensorBoard on the trace dir")
