"""paddle.profiler parity over the JAX/XLA profiler.

Reference parity: `python/paddle/profiler/profiler.py:224` (Profiler with
scheduler states CLOSED/READY/RECORD, `export_chrome_tracing`:128), the
statistics report (`profiler_statistic.py:1`), and the C++ host/device
tracers (`platform/profiler/host_tracer.cc`, `chrometracing_logger.cc`).

Two planes, as in the reference:
  - HOST: op-dispatch events hooked into `ops._dispatch.run_op` plus user
    `RecordEvent` ranges, collected in-process; `summary()` renders the
    per-op statistics table, `export()` writes chrome://tracing JSON.
  - DEVICE: the XLA profiler trace (TraceMe + device timeline) written to
    the trace dir for TensorBoard — the CUPTI-tracer role.
"""
from __future__ import annotations

import contextlib
import enum
import json
import os
import threading
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name

    # Profiler.__init__ reads the dir off the handler WITHOUT calling it, so
    # the handler itself runs only when a trace is ready (at stop) — the
    # reference's on_trace_ready contract (profiler.py:224).
    handler._export_dir = dir_name
    return handler


class _HostEvent:
    __slots__ = ("name", "start", "end", "tid", "kind")

    def __init__(self, name, start, end, tid, kind):
        self.name, self.start, self.end = name, start, end
        self.tid, self.kind = tid, kind

    @property
    def dur(self):
        return self.end - self.start


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        # dir-only peek: the handler itself runs when the trace is READY
        # (stop()), never here — see export_chrome_tracing
        self._export_dir = getattr(on_trace_ready, "_export_dir", None)
        self._active = False
        self.step_num = 0
        self._step_times = []
        self._t0 = None
        self._events: list = []
        self._lock = threading.Lock()

    # ---- lifecycle ----
    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        self._t0 = time.time()
        global _EXTERNAL_HOOK
        from ..ops import _dispatch
        if not _ACTIVE_STACK:
            # chain any hook a non-profiler party installed before us
            _EXTERNAL_HOOK = _dispatch._PROFILE_HOOK
        if self not in _ACTIVE_STACK:
            _ACTIVE_STACK.append(self)
        _dispatch._PROFILE_HOOK = _dispatch_hook
        if not self._timer_only:
            self._export_dir = self._export_dir or "./profiler_log"
            os.makedirs(self._export_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._export_dir)
                self._active = True
            except Exception:
                self._active = False
        return self

    def stop(self):
        # Stack discipline with out-of-order tolerance: remove THIS profiler
        # from the active set wherever it sits; the shared dispatcher hook
        # keeps feeding every remaining profiler, so stopping an outer
        # profiler never clobbers an inner one's hook (and nested profilers
        # both observe ops while both are active).
        global _EXTERNAL_HOOK
        from ..ops import _dispatch
        if self in _ACTIVE_STACK:
            _ACTIVE_STACK.remove(self)
        if not _ACTIVE_STACK:
            _dispatch._PROFILE_HOOK = _EXTERNAL_HOOK
            _EXTERNAL_HOOK = None
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.time()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        self.step_num += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        ts = np.asarray(self._step_times[-10:])
        return f"avg step {ts.mean()*1000:.2f} ms (last {len(ts)})"

    # ---- host events ----
    def _record_op(self, name, start, end, kind="op"):
        with self._lock:
            self._events.append(_HostEvent(name, start, end,
                                           threading.get_ident(), kind))

    def events(self):
        return list(self._events)

    # ---- statistics report (profiler_statistic.py role) ----
    def summary(self, sorted_by="total", op_detail=True, thread_sep=False,
                time_unit="ms"):
        scale = {"s": 1.0, "ms": 1e3, "us": 1e6}.get(time_unit, 1e3)
        stats = {}
        for e in self._events:
            s = stats.setdefault(e.name, [0, 0.0, float("inf"), 0.0])
            s[0] += 1
            s[1] += e.dur
            s[2] = min(s[2], e.dur)
            s[3] = max(s[3], e.dur)
        total = sum(s[1] for s in stats.values()) or 1e-12
        keyfn = (lambda kv: -kv[1][1]) if sorted_by in ("total", None) \
            else (lambda kv: -kv[1][0])
        lines = [
            "-" * 87,
            f"{'Name':<30}{'Calls':>7}{'Total(' + time_unit + ')':>14}"
            f"{'Avg':>9}{'Min':>9}{'Max':>9}{'Ratio':>8}",
            "-" * 87,
        ]
        for name, (cnt, tot, mn, mx) in sorted(stats.items(), key=keyfn):
            lines.append(
                f"{name[:29]:<30}{cnt:>7}{tot * scale:>14.3f}"
                f"{tot / cnt * scale:>9.3f}{mn * scale:>9.3f}"
                f"{mx * scale:>9.3f}{tot / total:>8.1%}")
        lines.append("-" * 87)
        if self._step_times:
            lines.append(self.step_info())
        return "\n".join(lines)

    # ---- chrome trace export (chrometracing_logger.cc role) ----
    def export(self, path, format="json"):
        events = []
        for e in self._events:
            events.append({"name": e.name, "ph": "X", "cat": e.kind,
                           "ts": e.start * 1e6, "dur": e.dur * 1e6,
                           "pid": os.getpid(), "tid": e.tid})
        # merge the obs plane: step-timeline phase spans ride along on their
        # own tids so one trace shows ops AND per-step phase attribution
        from .. import obs as _obs
        if _obs._TL_ENABLED:
            events.extend(_obs.timeline().chrome_events())
        # merge the stats plane: monitor counters ride along as metadata so
        # ONE artifact carries both spans and counters
        from .. import monitor as _monitor
        snap = _monitor.snapshot()
        events.append({"name": "paddle_tpu.monitor", "ph": "M",
                       "pid": os.getpid(), "tid": 0, "args": snap})
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms",
                       "monitor": snap}, f, default=str)
        return path


_ACTIVE_STACK: list = []
# hook that was installed on ops._dispatch before the first profiler started
# (chained by the dispatcher, restored when the last profiler stops)
_EXTERNAL_HOOK = None


def _dispatch_hook(name, start, end, kind="op"):
    """The ONE hook installed on ops._dispatch while any profiler is active:
    fans events out to every active profiler (nested profilers all observe
    ops) and chains to the pre-existing external hook, if any."""
    for p in tuple(_ACTIVE_STACK):
        p._record_op(name, start, end, kind)
    if _EXTERNAL_HOOK is not None:
        _EXTERNAL_HOOK(name, start, end)


@contextlib.contextmanager
def RecordEvent(name, event_type=None):
    """Host-side instrumentation range (`platform/profiler/event_tracing.h`).
    Recorded into every active Profiler's host events AND forwarded to the
    XLA TraceMe so it shows up on the device timeline."""
    t0 = time.time()
    with jax.profiler.TraceAnnotation(name):
        try:
            yield
        finally:
            t1 = time.time()
            for p in tuple(_ACTIVE_STACK):
                p._record_op(name, t0, t1, kind="user")


def load_profiler_result(filename):
    with open(filename) as f:
        return json.load(f)
