"""Quantization: QAT (fake-quant + STE), PTQ calibration, int8 weight-only.

Reference parity: `python/paddle/fluid/contrib/slim/quantization/`
(QuantizationTransformPass fake-quant insertion, `imperative/qat.py`
ImperativeQuantAware layer swap, PTQ calibration) and the inference-side
quantizer (`inference/api/mkldnn_quantizer.cc:1`).

TPU-native: fake-quant is a jnp straight-through estimator fused by XLA
into the surrounding matmul — no pass framework needed; the "transform
pass" is a Layer-tree swap (QuantedLinear/QuantedConv2D). True int8
storage is weight-only (per-channel symmetric), the useful TPU deployment
mode: int8 HBM + bf16 MXU compute after an on-chip dequant.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F


def _qrange(bits: int):
    return -(2 ** (bits - 1)), 2 ** (bits - 1) - 1


def _fq_arr(v, s, qmin, qmax):
    s = jnp.maximum(s, 1e-9)
    q = jnp.clip(jnp.round(v / s), qmin, qmax) * s
    # straight-through estimator: jax.vjp of this is identity wrt v
    return v + jax.lax.stop_gradient(q - v)


def fake_quant(x, scale, bits: int = 8):
    """Simulated symmetric quantization with a straight-through gradient:
    forward rounds to the int grid, backward passes through unchanged.
    Tensor inputs go through the op dispatch (`ops/_dispatch.run_op`) so the
    STE is recorded on the autograd tape — QAT gradients flow to the
    underlying weights/activations."""
    qmin, qmax = _qrange(bits)
    s = scale._value if isinstance(scale, Tensor) else jnp.asarray(scale)
    if isinstance(x, Tensor):
        from ..ops._dispatch import run_op
        return run_op(lambda v: _fq_arr(v, s, qmin, qmax), [x],
                      "fake_quantize_dequantize")
    return _fq_arr(x, s, qmin, qmax)


def abs_max_scale(w, bits: int = 8, channel_axis: Optional[int] = None):
    """Symmetric scale from the abs-max (per-tensor, or per output channel
    when channel_axis is given — the weight mode)."""
    v = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    _, qmax = _qrange(bits)
    if channel_axis is None:
        return jnp.max(jnp.abs(v)) / qmax
    axes = tuple(i for i in range(v.ndim) if i != channel_axis)
    return jnp.max(jnp.abs(v), axis=axes, keepdims=True) / qmax


class MovingAbsMaxObserver:
    """Activation-range observer (moving_average_abs_max in the reference)."""

    def __init__(self, momentum: float = 0.9, bits: int = 8):
        self.momentum = momentum
        self.bits = bits
        self._state: Optional[float] = None

    def update(self, x) -> float:
        v = x._value if isinstance(x, Tensor) else x
        if isinstance(v, jax.core.Tracer):
            raise RuntimeError(
                "MovingAbsMaxObserver cannot host-sync a traced value; "
                "quantized layers use per-batch dynamic scales under jit")
        cur = float(jnp.max(jnp.abs(v)))
        self._state = cur if self._state is None else \
            self.momentum * self._state + (1 - self.momentum) * cur
        return self.scale

    @property
    def scale(self) -> float:
        _, qmax = _qrange(self.bits)
        if self._state is None:
            raise RuntimeError(
                "observer was never calibrated: run at least one forward "
                "pass before freeze()/convert()")
        return max(self._state / qmax, 1e-9)


class _QuantedBase(nn.Layer):
    """Shared act-scale policy: frozen scale if converted; live observer in
    eager calibration/QAT; per-batch dynamic in-graph scale under jit
    (tracers can't feed the host-side observer)."""

    def __init__(self, bits: int):
        super().__init__()
        self.bits = bits
        self.act_observer = MovingAbsMaxObserver(bits=bits)
        self._frozen_act_scale: Optional[float] = None

    def _act_scale(self, x):
        if self._frozen_act_scale is not None:
            return self._frozen_act_scale
        v = x._value if isinstance(x, Tensor) else x
        if isinstance(v, jax.core.Tracer):
            return abs_max_scale(x, self.bits)
        return self.act_observer.update(x)

    def freeze(self):
        self._frozen_act_scale = self.act_observer.scale


class QuantedLinear(_QuantedBase):
    """Linear with fake-quant on activation (per-tensor) and weight
    (per-output-channel); shares the wrapped layer's parameters."""

    def __init__(self, layer: nn.Linear, bits: int = 8):
        super().__init__(bits)
        self.weight = layer.weight
        self.bias = layer.bias

    def forward(self, x):
        xq = fake_quant(x, self._act_scale(x), self.bits)
        wq = fake_quant(self.weight, abs_max_scale(self.weight, self.bits,
                                                   channel_axis=1), self.bits)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(_QuantedBase):
    def __init__(self, layer, bits: int = 8):
        super().__init__(bits)
        self._inner = layer

    def forward(self, x):
        xq = fake_quant(x, self._act_scale(x), self.bits)
        w = self._inner.weight
        wq = fake_quant(w, abs_max_scale(w, self.bits, channel_axis=0),
                        self.bits)
        return F.conv2d(xq, wq, self._inner.bias, self._inner.stride,
                        self._inner.padding, self._inner.dilation,
                        self._inner.groups, self._inner.data_format)


def quant_aware(model: nn.Layer, bits: int = 8) -> nn.Layer:
    """Swap quantizable sublayers for fake-quant twins IN PLACE
    (ImperativeQuantAware.quantize role). Returns the model."""
    for layer in list(model.sublayers(include_self=True)):
        for name, sub in list(layer._sub_layers.items()):
            if type(sub) is nn.Linear:
                layer._sub_layers[name] = QuantedLinear(sub, bits)
            elif type(sub) is nn.Conv2D:
                layer._sub_layers[name] = QuantedConv2D(sub, bits)
    return model


def freeze(model: nn.Layer) -> nn.Layer:
    """Freeze observers after calibration/QAT (convert role): scales become
    constants so the model jits/exports deterministically."""
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (QuantedLinear, QuantedConv2D)):
            layer.freeze()
    return model


class PTQ:
    """Post-training quantization driver (reference PTQ/mkldnn_quantizer):
    wrap -> run calibration batches -> freeze."""

    def __init__(self, bits: int = 8):
        self.bits = bits

    def quantize(self, model: nn.Layer) -> nn.Layer:
        return quant_aware(model, self.bits)

    def convert(self, model: nn.Layer) -> nn.Layer:
        return freeze(model)


# ---- true int8 storage (weight-only deployment) ----
def channel_quant(w: np.ndarray, bits: int = 8
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel symmetric int quantization of one weight array:
    (q int8, scale f32 broadcastable). Channel axis = out-features (axis 1
    for [in, out] linears, axis 0 for OIHW convs). Single source of truth
    for the int8 grid — jit.save's weight-only export uses it too."""
    w = np.asarray(w, dtype=np.float32)
    qmin, qmax = _qrange(bits)
    ch_axis = 1 if w.ndim == 2 else 0
    axes = tuple(i for i in range(w.ndim) if i != ch_axis)
    scale = np.maximum(np.abs(w).max(axis=axes, keepdims=True) / qmax,
                       1e-9).astype(np.float32)
    q = np.clip(np.round(w / scale), qmin, qmax).astype(np.int8)
    return q, scale


def quantize_weights(model: nn.Layer, bits: int = 8
                     ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Per-channel symmetric int8 of every 2-D+ weight: name -> (q, scale).
    Weights are REPLACED by their dequantized values in place (so accuracy
    impact is visible immediately); the returned dict is the artifact to
    ship (int8 HBM footprint)."""
    out = {}
    for name, p in model.named_parameters():
        if len(p.shape) < 2:
            continue
        q, scale = channel_quant(np.asarray(p._value), bits)
        out[name] = (q, scale)
        p._value = jnp.asarray(q.astype(np.float32) * scale)
    return out


def dequantize_weights(artifact: Dict[str, Tuple[np.ndarray, np.ndarray]]
                       ) -> Dict[str, np.ndarray]:
    return {k: q.astype(np.float32) * s for k, (q, s) in artifact.items()}


def _shadow_weight_only(layer: nn.Layer, dist_attr) -> None:
    """Replace `layer.forward` with an instance shadow that dequantizes
    the int8 buffers into a transient fp weight, delegates to the class
    forward, and removes the transient again. The dequant runs through
    `run_op`, so under `to_static` it is part of the trace (int8 HBM
    resident, fp dequant fused into the consuming matmul by XLA) and the
    int8 q + scale ride the buffer side of `jit.functional.split_state`.
    The transient `_parameters["weight"]` window makes this single-
    threaded per layer instance — the serving engines only dispatch from
    one scheduler thread."""
    inner = type(layer).forward

    def forward(*args, **kwargs):
        from ..ops._dispatch import run_op
        deq = run_op(lambda qa, sa: qa.astype(sa.dtype) * sa,
                     [layer.wo_weight_q, layer.wo_weight_scale],
                     "weight_only_dequant")
        deq.stop_gradient = True
        if dist_attr is not None:
            deq.dist_attr = dist_attr
        layer._parameters["weight"] = deq
        try:
            return inner(layer, *args, **kwargs)
        finally:
            del layer._parameters["weight"]

    layer.forward = forward


def quant_weight_only(model: nn.Layer, bits: int = 8) -> nn.Layer:
    """TRUE int8 weight-only conversion IN PLACE: every 2-D matmul weight
    (nn.Linear and the tensor-parallel ColumnParallelLinear /
    RowParallelLinear) is replaced by int8 `wo_weight_q` + per-channel
    f32 `wo_weight_scale` buffers; the fp Parameter is dropped from the
    layer. Embeddings stay fp (lookup tables dequantize per-row anyway
    and the GPT head is weight-tied to one). Unlike `quantize_weights`
    (fake storage: fp weights snapped to the grid), the model after this
    call genuinely holds int8 — state_dict carries q + scale, memory
    census sees the 4x smaller arrays — and dist_attr survives so mp
    sharding of the quantized buffers is unchanged. Inference-only:
    the weight Parameter no longer exists for optimizers. Returns model."""
    try:
        from ..parallel.mp_layers import ColumnParallelLinear, RowParallelLinear
        linear_types: tuple = (nn.Linear, ColumnParallelLinear,
                               RowParallelLinear)
    except Exception:  # parallel plane unavailable -> plain linears only
        linear_types = (nn.Linear,)
    converted = 0
    for layer in model.sublayers(include_self=True):
        if not isinstance(layer, linear_types):
            continue
        w = layer._parameters.get("weight")
        if w is None or len(w.shape) != 2:
            continue
        q, scale = channel_quant(np.asarray(w._value), bits)
        dist_attr = getattr(w, "dist_attr", None)
        layer.register_buffer("wo_weight_q", Tensor(jnp.asarray(q)))
        layer.register_buffer("wo_weight_scale", Tensor(jnp.asarray(scale)))
        del layer._parameters["weight"]
        _shadow_weight_only(layer, dist_attr)
        converted += 1
    if converted == 0:
        raise ValueError("quant_weight_only found no 2-D linear weights")
    return model
