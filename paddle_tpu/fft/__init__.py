"""paddle.fft parity over jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from ..ops._dispatch import ensure_tensor, run_op


def _wrap1(jfn, name):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return run_op(lambda a: jfn(a, n=n, axis=axis, norm=norm), [ensure_tensor(x)], name)

    op.__name__ = name
    return op


def _wrapn(jfn, name):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return run_op(lambda a: jfn(a, s=s, axes=axes, norm=norm), [ensure_tensor(x)], name)

    op.__name__ = name
    return op


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")
fft2 = _wrapn(lambda a, s=None, axes=None, norm=None: jnp.fft.fft2(a, s=s, axes=axes or (-2, -1), norm=norm), "fft2")
ifft2 = _wrapn(lambda a, s=None, axes=None, norm=None: jnp.fft.ifft2(a, s=s, axes=axes or (-2, -1), norm=norm), "ifft2")
rfft2 = _wrapn(lambda a, s=None, axes=None, norm=None: jnp.fft.rfft2(a, s=s, axes=axes or (-2, -1), norm=norm), "rfft2")
irfft2 = _wrapn(lambda a, s=None, axes=None, norm=None: jnp.fft.irfft2(a, s=s, axes=axes or (-2, -1), norm=norm), "irfft2")
fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return run_op(lambda a: jnp.fft.fftshift(a, axes=axes), [ensure_tensor(x)], "fftshift")


def ifftshift(x, axes=None, name=None):
    return run_op(lambda a: jnp.fft.ifftshift(a, axes=axes), [ensure_tensor(x)], "ifftshift")
