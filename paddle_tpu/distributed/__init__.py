"""paddle.distributed parity namespace — implemented by paddle_tpu.parallel.
This module re-exports it so user code can `import paddle.distributed`."""
from ..parallel import *  # noqa: F401,F403
from ..parallel import fleet  # noqa: F401
from . import auto_parallel  # noqa: F401
from .fleet_executor import (  # noqa: F401
    DistModel, FleetExecutor, InterceptorStuckError, PeerGoneError)
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401

# ---- remaining reference-surface members ----
from ..parallel import launch  # noqa: F401  (module: python -m ...launch)


class _PsEntryConfig:
    """Sparse-table entry (admission) policies for the PS tier
    (`distributed/entry_attr.py`): gate which feature ids get rows."""

    def __init__(self, kind, *args):
        self._kind = kind
        self._args = args

    def _to_attr(self):
        return ":".join([self._kind] + [str(a) for a in self._args])

    def __repr__(self):
        return f"{type(self).__name__}({', '.join(map(str, self._args))})"


class CountFilterEntry(_PsEntryConfig):
    """Admit a feature only after it has been seen `count` times."""

    def __init__(self, count: int):
        if count < 0:
            raise ValueError("count must be non-negative")
        super().__init__("count_filter_entry", int(count))


class ShowClickEntry(_PsEntryConfig):
    """Admission scored by named show/click input slots (CTR tables)."""

    def __init__(self, show_name: str, click_name: str):
        super().__init__("show_click_entry", show_name, click_name)


class ProbabilityEntry(_PsEntryConfig):
    """Admit a feature with the given probability."""

    def __init__(self, probability: float):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        super().__init__("probability_entry", float(probability))


# gloo_* compat: the reference uses Gloo for CPU-side barriers/rendezvous;
# this build's CPU control plane is the TCPStore + collective env, so these
# bind to it (same call sites, same semantics).
def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    from ..parallel import env as _env
    host, port = server_endpoint.rsplit(":", 1)
    from .._native import TCPStore
    store = TCPStore(host, int(port), is_master=(rank_id == 0),
                     world_size=rank_num)
    _GLOO_STATE["store"] = store
    _GLOO_STATE["rank"] = rank_id
    _GLOO_STATE["nranks"] = rank_num
    return store


_GLOO_STATE = {}


def gloo_barrier():
    store = _GLOO_STATE.get("store")
    if store is None:
        raise RuntimeError("gloo_barrier before gloo_init_parallel_env")
    import time as _t
    n = _GLOO_STATE["nranks"]
    gen = _GLOO_STATE.get("gen", 0)
    _GLOO_STATE["gen"] = gen + 1
    key = f"gloo_barrier/{gen}"
    arrived = store.add(key, 1)
    deadline = _t.time() + 60
    while arrived < n:
        if _t.time() > deadline:
            raise TimeoutError("gloo_barrier timed out")
        _t.sleep(0.01)
        arrived = store.add(key, 0)


def gloo_release():
    _GLOO_STATE.clear()


def split(x, num_or_sections, axis=0, name=None):
    """paddle.distributed.split compat — row/column-parallel splitting of a
    dense layer's computation is covered by mp_layers (ColumnParallelLinear
    / RowParallelLinear / VocabParallelEmbedding); the tensor-split form
    delegates to paddle.split."""
    from ..ops.manipulation import split as _split
    return _split(x, num_or_sections, axis)
