"""paddle.distributed parity namespace — implemented by paddle_tpu.parallel.
This module re-exports it so user code can `import paddle.distributed`."""
from ..parallel import *  # noqa: F401,F403
from ..parallel import fleet  # noqa: F401
from . import auto_parallel  # noqa: F401
from .fleet_executor import DistModel, FleetExecutor  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
