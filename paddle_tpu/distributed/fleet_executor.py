"""FleetExecutor — actor-style multi-program runtime + DistModel.

Reference parity: `paddle/fluid/distributed/fleet_executor/` — `Carrier` +
`Interceptor` message loops (`carrier.cc`, `interceptor.cc`,
`compute_interceptor.cc`), brpc `MessageBus` (`message_bus.cc`),
`RuntimeGraph`, and `DistModel` (`dist_model.cc`, the distributed
inference entry; AnalysisPredictor hands off to it at
`analysis_predictor.cc:1289`).

TPU-native redesign: interceptors are host-side actors (thread + queue)
whose "programs" are jitted XLA executables; the message bus is in-process
(cross-host hops ride the TCPStore/jax.distributed bring-up instead of
brpc). The scheduler's job on TPU is exactly the reference's: keep every
stage's chip busy by streaming microbatches through a DAG of compiled
segments, with credit-based flow control so upstream stages can't flood
downstream queues (compute_interceptor.cc's ready/credit counting).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class Message:
    __slots__ = ("src", "dst", "kind", "payload", "micro")

    def __init__(self, src: int, dst: int, kind: str, payload=None, micro=-1):
        self.src, self.dst, self.kind = src, dst, kind
        self.payload, self.micro = payload, micro


class MessageBus:
    """In-process router: interceptor id -> inbox (message_bus.cc role)."""

    def __init__(self):
        self._inboxes: Dict[int, "queue.Queue[Message]"] = {}

    def register(self, iid: int) -> "queue.Queue[Message]":
        q = self._inboxes.setdefault(iid, queue.Queue())
        return q

    def send(self, msg: Message):
        self._inboxes[msg.dst].put(msg)


class Interceptor:
    """Message-loop actor (interceptor.cc): one thread, one inbox."""

    def __init__(self, iid: int, bus: MessageBus):
        self.iid = iid
        self.bus = bus
        self.inbox = bus.register(iid)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            msg = self.inbox.get()
            if msg.kind == "stop":
                return
            try:
                self.handle(msg)
            except BaseException as e:
                self._error = e
                return

    def handle(self, msg: Message):
        raise NotImplementedError

    def send(self, dst: int, kind: str, payload=None, micro=-1):
        self.bus.send(Message(self.iid, dst, kind, payload, micro))

    def join(self):
        self.bus.send(Message(-1, self.iid, "stop"))
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._error is not None:
            raise RuntimeError(
                f"interceptor {self.iid} failed") from self._error


class ComputeInterceptor(Interceptor):
    """Runs its program on each upstream data message and forwards the
    result downstream, with credit-based backpressure
    (compute_interceptor.cc ready/credit counters)."""

    def __init__(self, iid, bus, fn: Callable, downstream: Optional[int],
                 upstream: Optional[int], max_inflight: int = 2):
        super().__init__(iid, bus)
        self.fn = fn
        self.downstream = downstream
        self.upstream = upstream
        self._credits = max_inflight  # slots downstream will accept
        self._pending: List[Message] = []

    def handle(self, msg: Message):
        if msg.kind == "credit":  # downstream freed a slot
            self._credits += 1
            self._drain()
            return
        if msg.kind == "data":
            self._pending.append(msg)
            self._drain()

    def _drain(self):
        while self._pending and (self._credits > 0 or self.downstream is None):
            msg = self._pending.pop(0)
            out = self.fn(msg.payload)
            if self.upstream is not None:
                # free our upstream's slot now that we consumed its output
                self.send(self.upstream, "credit")
            if self.downstream is not None:
                self._credits -= 1
                self.send(self.downstream, "data", out, msg.micro)


class SinkInterceptor(Interceptor):
    """Collects ordered results (the fetch side of the runtime graph)."""

    def __init__(self, iid, bus, n_expected: int, upstream: int):
        super().__init__(iid, bus)
        self.results: Dict[int, object] = {}
        self._n = n_expected
        self.upstream = upstream
        self.done = threading.Event()

    def handle(self, msg: Message):
        self.results[msg.micro] = msg.payload
        self.send(self.upstream, "credit")
        if len(self.results) >= self._n:
            self.done.set()


class FleetExecutor:
    """Carrier role: build the interceptor graph from a stage list and
    stream microbatches through it.

    stages: list of callables (typically jitted XLA programs — one per
    pipeline section, reference PipelineTrainer/SectionWorker analogue).
    """

    def __init__(self, stages: Sequence[Callable], max_inflight: int = 2):
        if not stages:
            raise ValueError("FleetExecutor needs at least one stage")
        self.stages = list(stages)
        self.max_inflight = max_inflight

    def run(self, microbatches: Sequence, timeout: float = 120.0) -> List:
        """Feed microbatches into stage 0; returns ordered stage-N outputs."""
        bus = MessageBus()
        n = len(self.stages)
        sink_id = n
        actors: List[Interceptor] = []
        for i, fn in enumerate(self.stages):
            actors.append(ComputeInterceptor(
                i, bus, fn,
                downstream=(i + 1) if i + 1 < n else sink_id,
                upstream=(i - 1) if i > 0 else None,
                max_inflight=self.max_inflight))
        sink = SinkInterceptor(sink_id, bus, len(microbatches), upstream=n - 1)
        actors.append(sink)
        for a in actors:
            a.start()
        for m, payload in enumerate(microbatches):
            bus.send(Message(-1, 0, "data", payload, m))
        import time as _time

        def join_all():
            # every actor gets its stop message even if one failed —
            # otherwise surviving threads block on inbox.get() forever
            first = None
            for a in actors:
                try:
                    a.join()
                except RuntimeError as e:
                    first = first or e
            return first

        deadline = _time.time() + timeout
        while not sink.done.is_set():
            if any(a._error is not None for a in actors):
                break  # fail fast: surface the stage error via join below
            if _time.time() > deadline:
                err = join_all()
                raise TimeoutError(
                    "FleetExecutor: pipeline did not drain") from err
            sink.done.wait(0.01)
        err = join_all()
        if err is not None:
            raise err
        return [sink.results[m] for m in range(len(microbatches))]


class DistModel:
    """Distributed inference entry (dist_model.cc role).

    Two regimes, mirroring the reference's mp/pp dist inference:
    - sharded: ONE jitted program over a mesh (GSPMD tensor/data parallel);
    - pipelined: stage programs streamed by the FleetExecutor actors.
    """

    def __init__(self, program: Optional[Callable] = None,
                 stages: Optional[Sequence[Callable]] = None,
                 mesh=None, in_spec=None, max_inflight: int = 2):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        if (program is None) == (stages is None):
            raise ValueError("give exactly one of program= or stages=")
        self._exe = None
        if program is not None:
            if mesh is not None:
                spec = P(*in_spec) if in_spec else P(tuple(mesh.axis_names)[0])
                self._exe = jax.jit(
                    program, in_shardings=NamedSharding(mesh, spec))
            else:
                self._exe = jax.jit(program)
        else:
            self._fleet = FleetExecutor(stages, max_inflight=max_inflight)

    def predict(self, x, n_micro: int = 1):
        import jax.numpy as jnp
        if self._exe is not None:
            return np.asarray(self._exe(jnp.asarray(x)))
        micros = np.array_split(np.asarray(x), n_micro)
        outs = self._fleet.run(micros)
        return np.concatenate([np.asarray(o) for o in outs], axis=0)
