"""FleetExecutor — actor-style multi-program runtime + DistModel.

Reference parity: `paddle/fluid/distributed/fleet_executor/` — `Carrier` +
`Interceptor` message loops (`carrier.cc`, `interceptor.cc`,
`compute_interceptor.cc`), brpc `MessageBus` (`message_bus.cc`),
`RuntimeGraph`, and `DistModel` (`dist_model.cc`, the distributed
inference entry; AnalysisPredictor hands off to it at
`analysis_predictor.cc:1289`).

TPU-native redesign: interceptors are host-side actors (thread + queue)
whose "programs" are jitted XLA executables; the message bus is in-process
(cross-host hops ride the TCPStore/jax.distributed bring-up instead of
brpc). The scheduler's job on TPU is exactly the reference's: keep every
stage's chip busy by streaming microbatches through a DAG of compiled
segments, with credit-based flow control so upstream stages can't flood
downstream queues (compute_interceptor.cc's ready/credit counting).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

try:
    from ..utils import syncwatch as _syncwatch
except ImportError:
    class _syncwatch:  # noqa: N801 — standalone: registry plane disabled
        Thread = threading.Thread

try:
    from .. import monitor as _monitor
except ImportError:
    # spec-loaded standalone (tests/fleet_exec_2proc_runner.py keeps this
    # module import-light, outside the package): stats plane disabled
    class _monitor:  # noqa: N801
        _ENABLED = False

try:
    from .. import faults as _faults
except ImportError:
    class _faults:  # noqa: N801 — standalone: injection plane disabled
        _ENABLED = False

try:
    from ..core import flags as _flags
except ImportError:
    _flags = None

try:
    from ..utils import net as _net
except ImportError:
    _net = None  # spec-loaded standalone: raw-socket fallback transport

# The bus codec reads frames on substrate-accepted connections, and the
# spec-loaded standalone runner keeps a raw-socket fallback transport
# (no package, no substrate) — both are deliberate, not a bypass.
# tpu-lint: disable=raw-socket

try:
    from ..obs import trace as _trace
except ImportError:
    class _NullBusSpan:  # standalone runner: tracing plane disabled
        trace_id = None

        def ctx(self):
            return None

        def set(self, **attrs):
            return self

        def end(self, status=None, **attrs):
            pass

    class _trace:  # noqa: N801
        _ENABLED = False
        NULL_SPAN = _NullBusSpan()
        STATUS_ERROR = "error"

        @staticmethod
        def pack_ctx(ctx):
            return b""

        @staticmethod
        def unpack_ctx(raw):
            return None

        @staticmethod
        def context():
            return None

        @staticmethod
        def server_span(name, ctx, attrs=None):
            return _trace.NULL_SPAN


def _bus_retry_config():
    """(retries, backoff_s) for the bus send path; flag-driven in-package,
    fixed defaults when spec-loaded standalone."""
    if _flags is None:
        return 3, 0.05
    return (int(_flags.flag("bus_send_retries")),
            float(_flags.flag("bus_send_backoff_ms")) / 1e3)


class PeerGoneError(RuntimeError):
    """A remote rank's bus endpoint is unreachable after reconnect
    retries — the peer process is gone (crashed/killed), not slow. Raised
    out of `DistMessageBus.send` and surfaced by `DistFleetExecutor.run`
    instead of letting the pipeline idle into its full run timeout."""

    def __init__(self, rank: int, msg: str):
        super().__init__(msg)
        self.rank = rank


class InterceptorStuckError(RuntimeError):
    """An interceptor thread outlived its join timeout — it is wedged
    (deadlocked handler or never-delivered stop), and silently leaking it
    would hide the hang."""


class Message:
    # trace_ctx: obs.trace.TraceContext carried across the bus (None for
    # untraced messages — the wire tuple then stays the legacy 5-tuple)
    __slots__ = ("src", "dst", "kind", "payload", "micro", "trace_ctx")

    def __init__(self, src: int, dst: int, kind: str, payload=None, micro=-1,
                 trace_ctx=None):
        self.src, self.dst, self.kind = src, dst, kind
        self.payload, self.micro = payload, micro
        self.trace_ctx = trace_ctx


class MessageBus:
    """In-process router: interceptor id -> inbox (message_bus.cc role)."""

    def __init__(self):
        self._inboxes: Dict[int, "queue.Queue[Message]"] = {}

    def register(self, iid: int) -> "queue.Queue[Message]":
        q = self._inboxes.setdefault(iid, queue.Queue())
        return q

    def send(self, msg: Message):
        q = self._inboxes[msg.dst]
        q.put(msg)
        if _monitor._ENABLED:
            _monitor.count("fleet.messages")
            _monitor.count(f"fleet.msg.{msg.kind}")
            _monitor.gauge_set(f"fleet.inbox_depth.{msg.dst}", q.qsize())


class Interceptor:
    """Message-loop actor (interceptor.cc): one thread, one inbox."""

    def __init__(self, iid: int, bus: MessageBus):
        self.iid = iid
        self.bus = bus
        self.inbox = bus.register(iid)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def start(self):
        self._thread = _syncwatch.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            msg = self.inbox.get()
            if msg.kind == "stop":
                return
            try:
                self.handle(msg)
            except BaseException as e:
                self._error = e
                return

    def handle(self, msg: Message):
        raise NotImplementedError

    def send(self, dst: int, kind: str, payload=None, micro=-1):
        self.bus.send(Message(self.iid, dst, kind, payload, micro))

    def join(self, send_stop: bool = True, timeout: float = 120.0):
        # send_stop=False: a remote carrier owns shutdown (its broadcast
        # stop message ends the loop) — sending our own here would kill
        # the actor with microbatches still queued behind backpressure
        if send_stop:
            self.bus.send(Message(-1, self.iid, "stop"))
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                raise InterceptorStuckError(
                    f"interceptor {self.iid} still alive {timeout}s after "
                    "join — wedged handler or undelivered stop message")
        if self._error is not None:
            if isinstance(self._error, PeerGoneError):
                raise self._error   # typed transport verdict, not a wrap
            raise RuntimeError(
                f"interceptor {self.iid} failed") from self._error


class ComputeInterceptor(Interceptor):
    """Runs its program on each upstream data message and forwards the
    result downstream, with credit-based backpressure
    (compute_interceptor.cc ready/credit counters)."""

    def __init__(self, iid, bus, fn: Callable, downstream: Optional[int],
                 upstream: Optional[int], max_inflight: int = 2):
        super().__init__(iid, bus)
        self.fn = fn
        self.downstream = downstream
        self.upstream = upstream
        self._credits = max_inflight  # slots downstream will accept
        self._pending: List[Message] = []

    def handle(self, msg: Message):
        if msg.kind == "credit":  # downstream freed a slot
            self._credits += 1
            self._drain()
            return
        if msg.kind == "data":
            self._pending.append(msg)
            self._drain()

    def _drain(self):
        if _monitor._ENABLED:
            _monitor.gauge_set(f"fleet.pending.{self.iid}",
                               len(self._pending))
        while self._pending and (self._credits > 0 or self.downstream is None):
            msg = self._pending.pop(0)
            out = self.fn(msg.payload)
            if self.upstream is not None:
                # free our upstream's slot now that we consumed its output
                self.send(self.upstream, "credit")
            if self.downstream is not None:
                self._credits -= 1
                self.send(self.downstream, "data", out, msg.micro)


class SinkInterceptor(Interceptor):
    """Collects ordered results (the fetch side of the runtime graph)."""

    def __init__(self, iid, bus, n_expected: int, upstream: int):
        super().__init__(iid, bus)
        self.results: Dict[int, object] = {}
        self._n = n_expected
        self.upstream = upstream
        self.done = threading.Event()

    def handle(self, msg: Message):
        self.results[msg.micro] = msg.payload
        self.send(self.upstream, "credit")
        if len(self.results) >= self._n:
            self.done.set()


class FleetExecutor:
    """Carrier role: build the interceptor graph from a stage list and
    stream microbatches through it.

    stages: list of callables (typically jitted XLA programs — one per
    pipeline section, reference PipelineTrainer/SectionWorker analogue).
    """

    def __init__(self, stages: Sequence[Callable], max_inflight: int = 2):
        if not stages:
            raise ValueError("FleetExecutor needs at least one stage")
        self.stages = list(stages)
        self.max_inflight = max_inflight

    def verify(self, sample):
        """Static task-graph check (tpu-lint stage-graph rule): prove each
        stage's output can feed the next stage by abstract evaluation,
        naming the first broken edge instead of hanging run() until its
        timeout. `sample` is an example stage-0 microbatch (array or
        ShapeDtypeStruct). Returns the findings list (empty = clean)."""
        from ..analysis.graph import verify_stage_chain
        return verify_stage_chain(self.stages, sample)

    def run(self, microbatches: Sequence, timeout: float = 120.0) -> List:
        """Feed microbatches into stage 0; returns ordered stage-N outputs."""
        bus = MessageBus()
        n = len(self.stages)
        sink_id = n
        actors: List[Interceptor] = []
        for i, fn in enumerate(self.stages):
            actors.append(ComputeInterceptor(
                i, bus, fn,
                downstream=(i + 1) if i + 1 < n else sink_id,
                upstream=(i - 1) if i > 0 else None,
                max_inflight=self.max_inflight))
        sink = SinkInterceptor(sink_id, bus, len(microbatches), upstream=n - 1)
        actors.append(sink)
        for a in actors:
            a.start()
        for m, payload in enumerate(microbatches):
            bus.send(Message(-1, 0, "data", payload, m))
        import time as _time

        def join_all():
            # every actor gets its stop message even if one failed —
            # otherwise surviving threads block on inbox.get() forever
            first = None
            for a in actors:
                try:
                    a.join()
                except RuntimeError as e:
                    first = first or e
            return first

        deadline = _time.time() + timeout
        while not sink.done.is_set():
            if any(a._error is not None for a in actors):
                break  # fail fast: surface the stage error via join below
            if _time.time() > deadline:
                err = join_all()
                raise TimeoutError(
                    "FleetExecutor: pipeline did not drain") from err
            sink.done.wait(0.01)
        err = join_all()
        if err is not None:
            raise err
        return [sink.results[m] for m in range(len(microbatches))]


class DistModel:
    """Distributed inference entry (dist_model.cc role).

    Two regimes, mirroring the reference's mp/pp dist inference:
    - sharded: ONE jitted program over a mesh (GSPMD tensor/data parallel);
    - pipelined: stage programs streamed by the FleetExecutor actors.
    """

    def __init__(self, program: Optional[Callable] = None,
                 stages: Optional[Sequence[Callable]] = None,
                 mesh=None, in_spec=None, max_inflight: int = 2):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        if (program is None) == (stages is None):
            raise ValueError("give exactly one of program= or stages=")
        self._exe = None
        if program is not None:
            if mesh is not None:
                spec = P(*in_spec) if in_spec else P(tuple(mesh.axis_names)[0])
                self._exe = jax.jit(
                    program, in_shardings=NamedSharding(mesh, spec))
            else:
                self._exe = jax.jit(program)
        else:
            self._fleet = FleetExecutor(stages, max_inflight=max_inflight)

    def predict(self, x, n_micro: int = 1):
        import jax.numpy as jnp
        if self._exe is not None:
            return np.asarray(self._exe(jnp.asarray(x)))
        micros = np.array_split(np.asarray(x), n_micro)
        outs = self._fleet.run(micros)
        return np.concatenate([np.asarray(o) for o in outs], axis=0)


# ---- cross-process message bus + carrier ------------------------------------
# The reference's MessageBus spans hosts over brpc (message_bus.cc: every
# Carrier registers its interceptor ids; InterceptorMessage routes by id).
# Here the transport is a per-process TCP listener + cached client sockets,
# with endpoints rendezvoused through the TCPStore (the same bootstrap path
# the collective env uses). Frames are length-prefixed pickles — a trusted
# control plane inside one training cluster, like the reference's RPC.

class DistMessageBus(MessageBus):
    """Message bus whose interceptors live across processes.

    owner_of: interceptor id -> rank. Local ids route to in-process
    queues; remote ids serialize over a socket to the owning rank's
    listener. Every rank must construct the bus (it publishes its
    endpoint under `fleetbus/{rank}` and resolves its peers').
    """

    def __init__(self, store, rank: int, nranks: int, owner_of: Dict[int, int],
                 host: str = "127.0.0.1"):
        super().__init__()
        import pickle
        import socket as _socket
        import struct as _struct
        import time as _time
        self._pickle, self._struct, self._socket = pickle, _struct, _socket
        self.rank, self.nranks = rank, nranks
        self.owner_of = dict(owner_of)
        self._send_retries, self._send_backoff = _bus_retry_config()
        self._was_connected: Dict[int, bool] = {}
        self._conns: Dict[int, object] = {}
        self._conn_lock = threading.Lock()       # guards the conn MAP only
        self._peer_locks: Dict[int, threading.Lock] = {}  # serialize frames
        self._stop = threading.Event()

        if _net is not None:
            self._lsock = _net.make_listener(host, 0, backlog=16)
        else:
            self._lsock = _socket.socket()
            self._lsock.setsockopt(_socket.SOL_SOCKET,
                                   _socket.SO_REUSEADDR, 1)
            self._lsock.bind((host, 0))
            self._lsock.listen(16)
        self._port = self._lsock.getsockname()[1]
        store.set(f"fleetbus/{rank}", f"{host}:{self._port}")
        self._accept_thread = _syncwatch.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

        self.endpoints: Dict[int, str] = {}
        deadline = _time.time() + 60
        for r in range(nranks):
            if r == rank:
                continue
            while True:
                try:
                    self.endpoints[r] = store.get(f"fleetbus/{r}").decode()
                    break
                except KeyError:
                    if _time.time() > deadline:
                        raise TimeoutError(
                            f"fleet bus: rank {r} endpoint never appeared")
                    _time.sleep(0.05)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            if _net is not None:
                try:
                    conn = _net.secure_server(conn, "bus")
                except (_net.AuthError, OSError, ValueError):
                    continue  # unauthenticated peer: counted + dropped
            _syncwatch.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn):
        import struct as _struct

        def _read_exact(n):
            buf = b""
            while len(buf) < n:
                chunk = conn.recv(min(1 << 20, n - len(buf)))
                if not chunk:
                    return None
                buf += chunk
            return buf

        try:
            while True:
                hdr = _read_exact(8)
                if hdr is None:
                    return
                (ln,) = _struct.unpack("<q", hdr)
                tctx = None
                if _net is not None and ln == _net.BUS_TRACE_SENTINEL:
                    # substrate trace carriage: the sentinel length
                    # prefixes `u32 ctx_len + ctx + i64 real_len`;
                    # untraced frames keep the legacy framing bit-for-bit
                    chdr = _read_exact(4)
                    if chdr is None:
                        return
                    (clen,) = _struct.unpack("<I", chdr)
                    if clen > 1024:
                        return  # corrupt carriage: unrecoverable stream
                    ctx_raw = _read_exact(clen)
                    lhdr = _read_exact(8)
                    if ctx_raw is None or lhdr is None:
                        return
                    (ln,) = _struct.unpack("<q", lhdr)
                    try:
                        tctx = _trace.unpack_ctx(ctx_raw)
                    except Exception:
                        tctx = None  # a trace must never break the bus
                if ln < 0:
                    return  # corrupt length: unrecoverable stream
                data = _read_exact(ln)
                if data is None:
                    return
                if _faults._ENABLED:
                    _faults.check("net.bus.recv")
                    _faults.check("bus.recv")
                # tolerant unpack: legacy traced peers append a 6th
                # element (the packed trace ctx); plain peers send the
                # 5-tuple; the substrate carriage above wins when present
                src, dst, kind, payload, micro, *rest = \
                    self._pickle.loads(data)
                if tctx is None and rest:
                    try:
                        tctx = _trace.unpack_ctx(rest[0])
                    except Exception:
                        tctx = None  # a trace must never break the bus
                msg = Message(src, dst, kind, payload, micro,
                              trace_ctx=tctx)
                # local delivery (register() may race: wait for the inbox)
                q = self._inboxes.get(msg.dst)
                if q is None:
                    import time as _time
                    for _ in range(600):
                        q = self._inboxes.get(msg.dst)
                        if q is not None:
                            break
                        _time.sleep(0.05)
                if q is None:
                    continue  # undeliverable after grace: drop (stop race)
                q.put(msg)
        except OSError:
            pass
        finally:
            conn.close()

    def _peer_lock(self, r: int) -> threading.Lock:
        with self._conn_lock:
            lk = self._peer_locks.get(r)
            if lk is None:
                lk = self._peer_locks[r] = threading.Lock()
            return lk

    def _chan(self, r: int):
        # substrate channel per peer: owns connect/reconnect (counted as
        # bus.reconnects) and the net.bus.send / bus.send fault sites.
        # Caller holds the PER-PEER lock; _conn_lock only guards the map.
        with self._conn_lock:
            ch = self._conns.get(r)
            if ch is None:
                ch = self._conns[r] = _net.RpcChannel(
                    "bus", endpoint=self.endpoints[r],
                    connect_timeout=60,
                    legacy_sites=("bus.send", None),
                    legacy_reconnect_counter="bus.reconnects")
            return ch

    def _remote_sock(self, r: int):
        # standalone raw-socket fallback (the in-package path rides
        # _chan); caller holds the PER-PEER lock, _conn_lock only guards
        # the map, so one slow peer's connect/send cannot head-of-line
        # block sends to every other peer
        with self._conn_lock:
            sk = self._conns.get(r)
        if sk is None:
            host, port = self.endpoints[r].rsplit(":", 1)
            sk = self._socket.create_connection((host, int(port)),
                                                timeout=60)
            sk.setsockopt(self._socket.IPPROTO_TCP,
                          self._socket.TCP_NODELAY, 1)
            with self._conn_lock:
                if self._was_connected.get(r):
                    if _monitor._ENABLED:
                        _monitor.count("bus.reconnects")
                self._was_connected[r] = True
                self._conns[r] = sk
        return sk

    def _drop_conn(self, r: int):
        # a failed send leaves the stream mid-frame: close and forget so
        # the retry opens a FRESH connection (frames never straddle one)
        if _net is not None:
            with self._conn_lock:
                ch = self._conns.get(r)
            if ch is not None:
                ch.drop()
            return
        with self._conn_lock:
            sk = self._conns.pop(r, None)
        if sk is not None:
            try:
                sk.close()
            except OSError:
                pass

    def send(self, msg: Message):
        owner = self.owner_of.get(msg.dst, self.rank)
        if _monitor._ENABLED:
            _monitor.count("fleet.messages")
            _monitor.count(f"fleet.msg.{msg.kind}")
            if owner != self.rank:
                _monitor.count("fleet.remote_messages")
        if owner == self.rank:
            self._inboxes[msg.dst].put(msg)
            return
        # serialize as a plain tuple: Message's defining module may be
        # loaded under a different name in the peer (spec-loaded runners).
        # Trace carriage rides the SUBSTRATE frame (sentinel length +
        # packed ctx) so untraced frames stay bit-identical to the legacy
        # 5-tuple; the standalone fallback keeps the 6th-element shim.
        tctx = None
        sp = _trace.NULL_SPAN
        if _trace._ENABLED:
            tctx = msg.trace_ctx or _trace.context()
            sp = _trace.server_span("bus.send", tctx,
                                    attrs={"dst": msg.dst,
                                           "kind": msg.kind})
        tup = (msg.src, msg.dst, msg.kind, msg.payload, msg.micro)
        ctx_raw = _trace.pack_ctx(tctx) if tctx is not None else b""
        if ctx_raw and _net is not None:
            data = self._pickle.dumps(
                tup, protocol=self._pickle.HIGHEST_PROTOCOL)
            frame = (self._struct.pack("<q", _net.BUS_TRACE_SENTINEL)
                     + self._struct.pack("<I", len(ctx_raw)) + ctx_raw
                     + self._struct.pack("<q", len(data)) + data)
        else:
            if ctx_raw:
                tup = tup + (ctx_raw,)  # legacy 6-tuple shim (standalone)
            data = self._pickle.dumps(
                tup, protocol=self._pickle.HIGHEST_PROTOCOL)
            frame = self._struct.pack("<q", len(data)) + data
        import time as _time
        with self._peer_lock(owner):
            delay = self._send_backoff
            last: Optional[BaseException] = None
            for attempt in range(self._send_retries + 1):
                if attempt:
                    if _net is not None:
                        _net._count("net.retries")
                        _net._count("net.bus.retries")
                    _time.sleep(delay)
                    delay = min(delay * 2, 2.0)
                try:
                    if _net is not None:
                        # fires net.bus.send + bus.send fault sites,
                        # reconnects (counted) through the channel
                        self._chan(owner).sendall(frame)
                    else:
                        if _faults._ENABLED:
                            _faults.check("bus.send")
                        sk = self._remote_sock(owner)
                        sk.sendall(frame)
                    sp.end(retries=attempt)
                    return
                except OSError as e:
                    last = e
                    self._drop_conn(owner)
            sp.end(status=_trace.STATUS_ERROR,
                   error=f"peer {owner} unreachable")
            raise PeerGoneError(
                owner,
                f"fleet bus: rank {owner} unreachable after "
                f"{self._send_retries + 1} attempts "
                f"({self.endpoints.get(owner, '?')}): {last}") from last

    def close(self):
        self._stop.set()
        # shutdown BEFORE close: a thread blocked in accept() pins the
        # listening socket's open file description, so close() alone
        # leaves the port accepting (and silently swallowing) frames
        # from peers that think this rank is still alive
        try:
            self._lsock.shutdown(self._socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._conn_lock:
            for sk in self._conns.values():
                try:
                    sk.close()
                except OSError:
                    pass
            self._conns.clear()


class DistFleetExecutor:
    """Carrier spanning processes: each rank hosts the interceptors of the
    stages it owns; microbatches stream across the bus exactly as in the
    single-process FleetExecutor (same ComputeInterceptor credit protocol,
    reference carrier.cc + compute_interceptor.cc over message_bus.cc).

    stage_owner: stage index -> rank. The sink interceptor (id =
    n_stages) lives with the LAST stage's rank and that rank's run()
    returns the ordered outputs; other ranks return None. The sink owner
    broadcasts the stop control messages, so every rank's run() joins
    cleanly (dist_model.cc's run-then-drain contract).
    """

    def __init__(self, my_stages: Dict[int, Callable], n_stages: int,
                 stage_owner: Dict[int, int], bus: DistMessageBus,
                 max_inflight: int = 2):
        self.my_stages = dict(my_stages)
        self.n_stages = n_stages
        self.stage_owner = dict(stage_owner)
        self.bus = bus
        self.max_inflight = max_inflight
        self.sink_id = n_stages
        self.sink_owner = stage_owner[n_stages - 1]
        owner_map = dict(stage_owner)
        owner_map[self.sink_id] = self.sink_owner
        bus.owner_of.update(owner_map)

    def verify(self):
        """Static ownership check of the distributed task graph (tpu-lint
        stage-graph rule): every stage must have exactly one owning rank
        and this rank must only host stages mapped to it — an unowned or
        doubly-hosted stage is a pipeline that hangs or double-consumes.
        Returns the findings list (empty = clean)."""
        from ..analysis.graph import verify_stage_assignment
        return verify_stage_assignment(self.stage_owner, self.n_stages,
                                       my_rank=self.bus.rank,
                                       my_stages=self.my_stages.keys())

    def run(self, microbatches: Optional[Sequence] = None, n_micro: int = 0,
            timeout: float = 120.0):
        rank = self.bus.rank
        n_micro = len(microbatches) if microbatches is not None else n_micro
        if n_micro <= 0:
            raise ValueError("run() needs microbatches or n_micro")
        actors: List[Interceptor] = []
        for sid, fn in self.my_stages.items():
            actors.append(ComputeInterceptor(
                sid, self.bus, fn,
                downstream=(sid + 1) if sid + 1 < self.n_stages
                else self.sink_id,
                upstream=(sid - 1) if sid > 0 else None,
                max_inflight=self.max_inflight))
        sink = None
        if rank == self.sink_owner:
            sink = SinkInterceptor(self.sink_id, self.bus, n_micro,
                                   upstream=self.n_stages - 1)
            actors.append(sink)
        for a in actors:
            a.start()
        if self.stage_owner[0] == rank:
            if microbatches is None:
                raise ValueError("the stage-0 rank must supply microbatches")
            for m, payload in enumerate(microbatches):
                self.bus.send(Message(-1, 0, "data", payload, m))
        import time as _time
        if sink is not None:
            deadline = _time.time() + timeout
            while not sink.done.is_set():
                if any(a._error is not None for a in actors):
                    break
                if _time.time() > deadline:
                    for sid in range(self.n_stages + 1):   # incl. the sink
                        self.bus.send(Message(-1, sid, "stop"))
                    raise TimeoutError("DistFleetExecutor: did not drain")
                sink.done.wait(0.01)
            # broadcast stop to EVERY stage cluster-wide, then our sink
            for sid in range(self.n_stages):
                self.bus.send(Message(-1, sid, "stop"))
        first = None
        for a in actors:
            try:
                # only the sink owner originates stops (broadcast above);
                # other ranks wait for those to arrive over the bus
                a.join(send_stop=(sink is not None and a is sink))
            except RuntimeError as e:
                first = first or e
        if first is not None:
            raise first
        if sink is not None:
            return [sink.results[m] for m in range(n_micro)]
        return None
