"""Parameter-server tier (CTR/recommendation workload class).

Reference parity: the python runtime facade `distributed/ps/the_one_ps.py`
over the C++ PS (`ps/service/brpc_ps_server.cc`, tables in `ps/table/`),
plus `distributed_lookup_table_op` (`operators/pscore/`) as the trainer-side
sparse pull/push op.

TPU-native split: sparse embedding tables live on CPU PS hosts; the trainer
pulls just the batch's rows, runs the DENSE model on the chip, and pushes
sparse grads back asynchronously through the Communicator — identical
dataflow to the reference's DownpourWorker (SURVEY §3.5), with the dense
hot path jitted on TPU.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .table import DenseTable, SparseTable  # noqa: F401
from .service import (Communicator, CommunicatorFlushTimeout,  # noqa: F401
                      PsClient, PsError, PsServer)
from .native import NativePsServer  # noqa: F401
from .wal import PsSnapshotUnsupportedError, SeqLedger, WalWriter  # noqa: F401
from .ha import HaPsNode, connect as ha_connect_client  # noqa: F401
from .delta import DeltaBatch, DeltaSubscriber, rpc_delta  # noqa: F401


class PsContext:
    """the_one_ps-style runtime facade driven by TRAINING_ROLE env."""

    def __init__(self):
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self.server_endpoints = [e for e in eps.split(",") if e]
        self.server: Optional[PsServer] = None
        self.client: Optional[PsClient] = None
        self.communicator: Optional[Communicator] = None

    def is_server(self):
        return self.role == "PSERVER"

    def init_server(self, host="127.0.0.1", port=0) -> PsServer:
        self.server = PsServer(host, port)
        return self.server

    def run_server(self, block=False):
        return self.server.run(block=block)

    def init_worker(self, endpoints=None) -> PsClient:
        # re-read the env each time: the documented flow sets
        # PADDLE_PSERVERS_IP_PORT_LIST AFTER the server binds its port
        if endpoints is not None:
            self.server_endpoints = list(endpoints)
        else:
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            parsed = [e for e in eps.split(",") if e]
            if parsed:
                self.server_endpoints = parsed
        if not self.server_endpoints:
            raise RuntimeError(
                "init_worker: no PS endpoints — set "
                "PADDLE_PSERVERS_IP_PORT_LIST or pass endpoints=")
        self.client = PsClient(self.server_endpoints)
        self.communicator = Communicator(self.client)
        return self.client

    def stop_worker(self):
        try:
            if self.communicator is not None:
                self.communicator.stop()
        finally:
            if self.client is not None:
                self.client.close()


class DistributedEmbedding:
    """Sparse embedding backed by a PS sparse table
    (`distributed_lookup_table_op` role).

    forward: pull rows for the batch ids (host RPC) -> device tensor;
    backward: the tape node pushes row grads to the PS via the async
    Communicator (the DownpourWorker push_gradients path)."""

    def __init__(self, client: PsClient, table: str, dim: int,
                 communicator: Optional[Communicator] = None):
        self.client = client
        self.table = table
        self.dim = dim
        self.communicator = communicator
        client.register_sparse_dim(table, dim)

    def __call__(self, ids):
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        from ...core import autograd

        ids_np = np.asarray(ids._value if isinstance(ids, Tensor) else ids,
                            np.int64)
        flat = ids_np.reshape(-1)
        rows = self.client.pull_sparse(self.table, flat)  # [N, dim] host
        out = Tensor(jnp.asarray(rows.reshape(*ids_np.shape, self.dim)))

        if autograd.is_grad_enabled():
            client, table, comm = self.client, self.table, self.communicator

            def vjp(g):
                g_np = np.asarray(g, np.float32).reshape(len(flat), self.dim)
                if comm is not None:
                    comm.push_sparse_async(table, flat, g_np)
                else:
                    client.push_sparse(table, flat, g_np)
                return ()  # no upstream grads: ids are integers

            autograd.record_node(vjp, [], [out], "distributed_lookup_table")
        return out
