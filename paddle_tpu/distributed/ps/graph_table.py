"""GraphTable — node/edge store + neighbor sampling on the PS plane.

Reference parity: `paddle/fluid/distributed/ps/table/common_graph_table.h:355`
(GraphTable: edge lists per node with optional weights, node features,
`random_sample_neighbors`, `random_sample_nodes`, `get_node_feat`) — the
GNN-sampling backend PGL drives through the PS service.

TPU-first contract: `sample_neighbors` returns FIXED-SHAPE [n, k] id/weight
arrays (pad id -1), sampling with replacement — downstream GNN minibatch
programs keep static shapes and jit without data-dependent padding logic.
The store itself is host-side (the reference's is too — graph sampling is
a CPU-side service feeding the accelerator).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np


class GraphTable:
    def __init__(self, weighted: bool = True, feat_dim: int = 0, seed: int = 0):
        self._lock = threading.Lock()
        self.weighted = weighted
        self.feat_dim = int(feat_dim)
        self._adj: Dict[int, List[int]] = {}
        self._w: Dict[int, List[float]] = {}
        self._feat: Dict[int, np.ndarray] = {}
        self._rng = np.random.default_rng(seed)

    # ---- construction (load_edges / load_nodes roles) ----
    def add_edges(self, src, dst, weight=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = (np.asarray(weight, np.float32).reshape(-1) if weight is not None
             else np.ones(len(src), np.float32))
        if not (len(src) == len(dst) == len(w)):
            raise ValueError("add_edges: src/dst/weight length mismatch")
        with self._lock:
            for s, d, wt in zip(src, dst, w):
                self._adj.setdefault(int(s), []).append(int(d))
                self._w.setdefault(int(s), []).append(float(wt))
                self._adj.setdefault(int(d), self._adj.get(int(d), []))

    def set_node_feat(self, ids, feats):
        ids = np.asarray(ids, np.int64).reshape(-1)
        feats = np.asarray(feats, np.float32).reshape(len(ids), -1)
        if self.feat_dim and feats.shape[1] != self.feat_dim:
            raise ValueError(
                f"feat dim {feats.shape[1]} != table feat_dim {self.feat_dim}")
        with self._lock:
            for i, f in zip(ids, feats):
                self._feat[int(i)] = f.copy()

    # ---- queries ----
    def n_nodes(self) -> int:
        with self._lock:
            return len(self._adj)

    def neighbors(self, node: int):
        with self._lock:
            return (list(self._adj.get(int(node), [])),
                    list(self._w.get(int(node), [])))

    def sample_neighbors(self, ids, k: int):
        """[n] ids -> ([n, k] neighbor ids, [n, k] weights); pad -1/0.
        Weighted tables sample proportionally to edge weight (reference
        WeightedSampler); unweighted uniformly; always with replacement."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.full((len(ids), k), -1, np.int64)
        ow = np.zeros((len(ids), k), np.float32)
        with self._lock:
            for r, i in enumerate(ids):
                nbrs = self._adj.get(int(i))
                if not nbrs:
                    continue
                w = np.asarray(self._w[int(i)], np.float64)
                p = w / w.sum() if self.weighted and w.sum() > 0 else None
                sel = self._rng.choice(len(nbrs), size=k, replace=True, p=p)
                out[r] = np.asarray(nbrs, np.int64)[sel]
                ow[r] = np.asarray(self._w[int(i)], np.float32)[sel]
        return out, ow

    def random_sample_nodes(self, k: int):
        with self._lock:
            pool = np.fromiter(self._adj.keys(), np.int64, len(self._adj))
        if len(pool) == 0:
            return np.empty(0, np.int64)
        return self._rng.choice(pool, size=min(k, len(pool)), replace=False)

    def get_node_feat(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        d = self.feat_dim or (next(iter(self._feat.values())).shape[0]
                              if self._feat else 0)
        out = np.zeros((len(ids), d), np.float32)
        with self._lock:
            for r, i in enumerate(ids):
                f = self._feat.get(int(i))
                if f is not None:
                    out[r, :len(f)] = f
        return out

    def state(self):
        return {"adj": self._adj, "w": self._w, "feat": self._feat}

    # ---- durability (rides the PS snapshot/fetch-state plane) ----
    def snapshot_arrays(self) -> Dict[str, np.ndarray]:
        """Deterministic flat-array form for atomic_write+CRC snapshots:
        edges as (src, dst, weight) triples iterated over SORTED source
        nodes with per-node insertion order preserved (the order
        `sample_neighbors` indexes by), isolated nodes separately, and
        features sorted by key. Same table content -> same bytes, so a
        restart restore is bit-identical."""
        with self._lock:
            src: List[int] = []
            dst: List[int] = []
            w: List[float] = []
            iso: List[int] = []
            for s in sorted(self._adj.keys()):
                nbrs = self._adj[s]
                if not nbrs:
                    iso.append(s)
                    continue
                src.extend([s] * len(nbrs))
                dst.extend(nbrs)
                w.extend(self._w.get(s, [1.0] * len(nbrs)))
            fkeys = sorted(self._feat.keys())
            fdim = (self._feat[fkeys[0]].shape[0] if fkeys
                    else max(self.feat_dim, 1))
            fvals = (np.stack([self._feat[k] for k in fkeys])
                     if fkeys else np.zeros((0, fdim), np.float32))
            return {
                "edge_src": np.asarray(src, np.int64),
                "edge_dst": np.asarray(dst, np.int64),
                "edge_w": np.asarray(w, np.float32),
                "iso_nodes": np.asarray(iso, np.int64),
                "feat_keys": np.asarray(fkeys, np.int64),
                "feat_vals": fvals.astype(np.float32),
            }

    def load_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        """Restore from `snapshot_arrays` output (replaces content)."""
        with self._lock:
            self._adj.clear()
            self._w.clear()
            self._feat.clear()
            for s, d, wt in zip(arrays["edge_src"], arrays["edge_dst"],
                                arrays["edge_w"]):
                self._adj.setdefault(int(s), []).append(int(d))
                self._w.setdefault(int(s), []).append(float(wt))
                self._adj.setdefault(int(d), self._adj.get(int(d), []))
            for n in arrays.get("iso_nodes", ()):
                self._adj.setdefault(int(n), [])
            feat_vals = np.asarray(arrays.get(
                "feat_vals", np.zeros((0, 1), np.float32)), np.float32)
            for i, k in enumerate(arrays.get("feat_keys", ())):
                self._feat[int(k)] = feat_vals[i].copy()
