"""Delta-push plane: trainer-side PS -> serving replicas, value-shipped.

A serving replica subscribes to a sparse table like a standby tails the
WAL (same watermark discipline as CMD_REPLICATE), but the payload is
embedding ROWS, not log records: the server ships the CURRENT value of
every row touched since the subscriber's watermark, plus tombstones for
TTL-shrink evictions. Value-shipping makes installs idempotent — a
retried pull after a torn response re-installs the same values, so the
plane is exactly-once-EFFECTIVE without a serving-side ledger — and
keeps optimizer slots (which serving never reads) off the wire.

Watermarks are commit versions: the WAL lsn on durable servers (the one
monotonic version that survives restart and failover), a local commit
counter otherwise. A subscriber below the server's resync floor (fresh
subscriber, or the server just recovered / installed a fetched state)
gets a full-table replace instead of a merge.

Wire (CMD_DELTA, service.py header conventions):
  request:  HDR(CMD_DELTA, table, 0, 0) + i64 after_version
            + i64 max_rows + i64 id_len + subscriber id
  response: 0x01 + i64 version + i64 dim + i64 flags(bit0=full)
            + i64 n_live + i64 n_dead
            + live_keys i64[n_live] + rows f32[n_live, dim]
            + dead_keys i64[n_dead]

Fault site: `ps.delta.push` fires on the server's send (check + torn
mangle), exercised alongside the other PS-plane seams in the online
soak.
"""
# tpu-lint: disable=raw-socket
from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import numpy as np

from .service import (CMD_DELTA, PsError, _HDR, _LEN, _check_status,
                      _recv_exact, _tname, ha_connect)
from ... import faults as _faults
from ... import monitor as _monitor
from ...core import flags as _flags
from ...utils import net as _net
from ...utils import syncwatch as _syncwatch

__all__ = ["DeltaBatch", "DeltaSubscriber", "rpc_delta", "serve_delta"]

# live subscribers, for the conftest leak guard (`_no_ps_leak`)
_LIVE = weakref.WeakSet()

_MAX_DELTA_ROWS = 100_000_000
_MAX_DELTA_DIM = 1_000_000


@dataclass
class DeltaBatch:
    """One CMD_DELTA response, decoded."""
    version: int
    dim: int
    full: bool              # True: replace the whole table, don't merge
    live_keys: np.ndarray   # i64 [n_live]
    rows: np.ndarray        # f32 [n_live, dim]
    dead_keys: np.ndarray   # i64 [n_dead]


def serve_delta(server, conn, name: str, after_version: int,
                max_rows: int, subscriber: str) -> None:
    """Server side of CMD_DELTA (called from PsServer._handle; errors
    propagate to the handler's error-frame path). The response frames
    go out scatter-gather — the stacked row block is handed to the
    kernel as-is, never re-joined with the key arrays."""
    version, dim, full, live, rows, dead = server.delta_since(
        name, after_version, max_rows, subscriber)
    head = (b"\x01" + _LEN.pack(int(version)) + _LEN.pack(int(dim))
            + _LEN.pack(1 if full else 0) + _LEN.pack(len(live))
            + _LEN.pack(len(dead)))
    frames = [head,
              np.asarray(live, np.int64).tobytes(),
              np.ascontiguousarray(rows, np.float32).tobytes(),
              np.asarray(dead, np.int64).tobytes()]
    if _faults._ENABLED:
        payload = b"".join(frames)
        _faults.check("ps.delta.push")
        payload = _faults.mangle("ps.delta.push", payload)
        conn.sendall(payload)
    else:
        _net.send_frames(conn, frames)
    if _monitor._ENABLED:
        _monitor.count("ps.delta.pushes")
        if len(live) or len(dead):
            _monitor.count("ps.delta.rows_shipped", len(live) + len(dead))


def rpc_delta(sock, table: str, after_version: int = -1, max_rows: int = 0,
              subscriber_id: str = "", deadline=None) -> DeltaBatch:
    """Pull one delta batch. `after_version` doubles as the caller's ack
    watermark (-1 = nothing installed yet -> full bootstrap). Callers
    polling an unreliable wire should pass a `deadline`: a torn response
    then raises instead of blocking forever."""
    sid = subscriber_id.encode()
    sock.sendall(_HDR.pack(CMD_DELTA, _tname(table), 0, 0)
                 + _LEN.pack(int(after_version)) + _LEN.pack(int(max_rows))
                 + _LEN.pack(len(sid)) + sid)
    _check_status(sock, deadline)
    version, dim, flags, n_live, n_dead = (
        _LEN.unpack(_recv_exact(sock, 8, deadline))[0] for _ in range(5))
    if not (0 < dim <= _MAX_DELTA_DIM
            and 0 <= n_live <= _MAX_DELTA_ROWS
            and 0 <= n_dead <= _MAX_DELTA_ROWS):
        raise PsError(f"delta: implausible response header dim={dim} "
                      f"n_live={n_live} n_dead={n_dead}")
    live = np.frombuffer(_recv_exact(sock, 8 * n_live, deadline), np.int64)
    rows = np.frombuffer(_recv_exact(sock, 4 * n_live * dim, deadline),
                         np.float32).reshape(n_live, dim)
    dead = np.frombuffer(_recv_exact(sock, 8 * n_dead, deadline), np.int64)
    return DeltaBatch(int(version), int(dim), bool(flags & 1),
                      live, rows, dead)


class DeltaSubscriber:
    """Background tail of one PS's delta stream into serving tables.

    `tables` maps PS table name -> install target (an
    `serving.online.OnlineServingTable`, or anything with
    `install_delta(batch)` + `mark_fresh()`). The endpoint comes from a
    static `endpoint` or a `resolver()` callable (use `ha.resolver(store)`
    so the tail follows a failover to the promoted standby).

    Loss/duplication contract: the watermark advances ONLY after a
    batch installed successfully (zero loss — a crash between pull and
    install re-pulls the same rows), and installs are idempotent value
    writes (zero double-apply effects). An empty delta still marks the
    table fresh: "nothing changed" is a successful sync, not staleness.
    Transport errors drop the connection, count
    `ps.delta.pull_errors`, and the next tick re-resolves — the
    subscriber never dies with the primary.
    """

    def __init__(self, tables: Dict[str, object], endpoint: str = None,
                 resolver: Optional[Callable] = None,
                 subscriber_id: str = "serving",
                 interval_ms: Optional[float] = None,
                 max_rows: Optional[int] = None,
                 pull_timeout_s: float = 10.0):
        if endpoint is None and resolver is None:
            raise ValueError("DeltaSubscriber needs an endpoint or resolver")
        self.tables = dict(tables)
        self._endpoint = endpoint
        self._resolver = resolver
        self.subscriber_id = subscriber_id
        self._interval_s = (float(_flags.flag("online_delta_interval_ms"))
                            if interval_ms is None else interval_ms) / 1e3
        self._max_rows = (int(_flags.flag("online_delta_max_rows"))
                          if max_rows is None else int(max_rows))
        self._pull_timeout_s = pull_timeout_s
        self._marks: Dict[str, int] = {t: -1 for t in self.tables}
        self._sock = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _LIVE.add(self)

    def watermark(self, table: str) -> int:
        return self._marks[table]

    def _connect(self):
        if self._sock is not None:
            return self._sock
        ep = self._endpoint
        if self._resolver is not None:
            eps = self._resolver()
            ep = eps[0] if eps else None
        if ep is None:
            raise ConnectionError("delta: no endpoint resolved")
        self._sock = ha_connect(ep)
        return self._sock

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def poll_once(self) -> int:
        """One pull+install pass over every table; returns rows applied.
        Raises on transport failure (the loop counts and retries; direct
        callers — tests, the bench — see the real error)."""
        applied = 0
        for name, target in self.tables.items():
            deadline = time.monotonic() + self._pull_timeout_s
            # keep pulling while a max_rows cap leaves us behind the head
            while True:
                sock = self._connect()
                try:
                    batch = rpc_delta(
                        sock, name, after_version=self._marks[name],
                        max_rows=self._max_rows,
                        subscriber_id=self.subscriber_id, deadline=deadline)
                except BaseException:
                    self._drop()
                    raise
                target.install_delta(batch)
                self._marks[name] = batch.version  # install-then-advance
                target.mark_fresh()
                applied += len(batch.live_keys) + len(batch.dead_keys)
                if not (len(batch.live_keys) or len(batch.dead_keys)) \
                        or not self._max_rows:
                    break
        return applied

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except (OSError, PsError, ValueError, TimeoutError):
                if _monitor._ENABLED:
                    _monitor.count("ps.delta.pull_errors")
                self._drop()
            self._wake.wait(self._interval_s)
            self._wake.clear()

    def start(self) -> "DeltaSubscriber":
        self._thread = _syncwatch.Thread(target=self._loop, daemon=True,
                                        name="ps-delta-tail")
        self._thread.start()
        return self

    def kick(self) -> None:
        """Wake the tail immediately (tests and cutover probes)."""
        self._wake.set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._drop()
