"""PS durability plane: write-ahead delta log + crash-atomic snapshots.

Reference parity: the reference's PS persists sparse tables through
`ps/table/` save/load plus an incremental "delta" path for online
learning; brpc PS deployments pair that with warm standbys. Here the
same roles are built from the repo's own primitives: the delta log is a
segmented, CRC-framed record stream (one record per mutating RPC,
stamped with the client's existing push seq), and compaction reuses the
`sharded_io.atomic_write` + CRC-manifest + `.bak`-generation commit
protocol the guard plane already trusts (`guard/checkpoint.py`).

Recovery contract: restart = load the newest INTACT snapshot generation
(manifest -> payload, falling back to the `.bak` generation on a CRC
mismatch, counting `ps.wal.fallbacks`), then replay WAL records with
lsn > snapshot lsn, dedup'd by the persisted `SeqLedger` — so the
at-most-once server ledger itself survives restart and a trainer retry
replayed across the crash is still exactly-once. A torn tail record
(SIGKILL mid-append, or the `ps.wal.write` fault site) ends replay at
the last intact record; it is never an error, because a torn record was
by construction never applied nor ACKed.

Fault sites: `ps.wal.write` (torn/short append, via `faults.mangle`)
and `ps.snapshot.commit` (crash point between the snapshot payload and
its manifest — the manifest keeps referencing the previous generation).
"""
from __future__ import annotations

import glob
import io
import json
import os
import struct
import threading
import weakref
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

import numpy as np

from ... import faults as _faults
from ... import monitor as _monitor
from ...framework.sharded_io import atomic_write, _crc

__all__ = [
    "PsSnapshotUnsupportedError", "Record", "SeqLedger", "WalWriter",
    "encode_record", "decode_record", "replay", "save_snapshot",
    "load_snapshot", "gc_segments", "wal_status",
    "pack_push_sparse", "unpack_push_sparse", "pack_push_dense",
    "unpack_push_dense", "pack_show_click", "unpack_show_click",
]


class PsSnapshotUnsupportedError(TypeError):
    """A registered table kind has no snapshot representation (graph
    tables) — raised instead of silently dropping its state."""


# record types (one per mutating RPC verb + table registration)
R_PUSH_SPARSE = 1
R_PUSH_DENSE = 2
R_SHOW_CLICK = 3
R_DECAY = 4
R_SHRINK = 5
R_ADD_SPARSE = 6     # payload: JSON table config
R_ADD_DENSE = 7
R_ADD_GRAPH = 8      # registration only: graph CONTENT rides snapshots

# lsn, rtype, table name (padded), client id (padded), seq, payload len
_REC_HDR = struct.Struct("<qB16s16sqq")
_CRC32 = struct.Struct("<I")
_LEN = struct.Struct("<q")

_SEG_GLOB = "wal-*.log"
_MANIFEST = "ps-manifest.json"

# open WalWriters, for the conftest leak guard (`_no_ps_leak`)
_LIVE_WRITERS: "weakref.WeakSet[WalWriter]" = weakref.WeakSet()


class Record(NamedTuple):
    lsn: int
    rtype: int
    table: str
    client: str       # "" for unsequenced records
    seq: int          # -1 for unsequenced records
    payload: bytes


def _pad16(s: str) -> bytes:
    b = s.encode()
    if len(b) > 16:
        raise ValueError(f"wal name {s!r} exceeds the 16-byte wire limit")
    return b.ljust(16, b"\0")


def encode_record(rec: Record) -> bytes:
    body = _REC_HDR.pack(rec.lsn, rec.rtype, _pad16(rec.table),
                         _pad16(rec.client), rec.seq, len(rec.payload)) \
        + rec.payload
    return body + _CRC32.pack(_crc(body))


def decode_record(raw: bytes) -> Record:
    """Decode one framed record; raises ValueError on any damage."""
    if len(raw) < _REC_HDR.size + _CRC32.size:
        raise ValueError("wal record too short")
    body, (crc,) = raw[:-_CRC32.size], _CRC32.unpack(raw[-_CRC32.size:])
    if _crc(body) != crc:
        raise ValueError("wal record failed its checksum")
    lsn, rtype, table, client, seq, plen = _REC_HDR.unpack(
        body[:_REC_HDR.size])
    payload = body[_REC_HDR.size:]
    if len(payload) != plen:
        raise ValueError("wal record payload length mismatch")
    return Record(lsn, rtype, table.rstrip(b"\0").decode(),
                  client.rstrip(b"\0").decode(), seq, payload)


def decode_stream(blob: bytes) -> List[Record]:
    """Decode a concatenation of framed records (the REPLICATE/HANDBACK
    wire form). Raises ValueError on damage — this blob crossed a
    checksummed RPC, so damage is a bug, not a torn tail."""
    out: List[Record] = []
    off = 0
    while off < len(blob):
        if off + _REC_HDR.size > len(blob):
            raise ValueError("ps record stream truncated")
        _, _, _, _, _, plen = _REC_HDR.unpack_from(blob, off)
        end = off + _REC_HDR.size + plen + _CRC32.size
        if plen < 0 or end > len(blob):
            raise ValueError("ps record stream truncated")
        out.append(decode_record(blob[off:end]))
        off = end
    return out


def wipe(dirname: str) -> None:
    """Remove every WAL segment, snapshot payload, and manifest — the
    rejoin flow resets a superseded durability chain before re-anchoring
    on the new primary's state."""
    for pat in (_SEG_GLOB, "ps-snap-v*.npz", _MANIFEST, _MANIFEST + ".bak",
                "ha-status.json"):
        for p in glob.glob(os.path.join(dirname, pat)):
            try:
                os.remove(p)
            except OSError:
                pass


# ---- delta payload codecs (shared by the RPC handler, replay, and the
#      replication/handback wire) -----------------------------------------

def pack_push_sparse(ids: np.ndarray, grads: np.ndarray) -> bytes:
    return (_LEN.pack(len(ids)) + _LEN.pack(grads.shape[1])
            + np.ascontiguousarray(ids, np.int64).tobytes()
            + np.ascontiguousarray(grads, np.float32).tobytes())


def unpack_push_sparse(payload: bytes) -> Tuple[np.ndarray, np.ndarray]:
    (n,) = _LEN.unpack_from(payload, 0)
    (dim,) = _LEN.unpack_from(payload, 8)
    ids = np.frombuffer(payload, np.int64, n, 16)
    grads = np.frombuffer(payload, np.float32, n * dim,
                          16 + 8 * n).reshape(n, dim)
    return ids, grads


def pack_push_dense(grads: np.ndarray) -> bytes:
    return np.ascontiguousarray(grads, np.float32).tobytes()


def unpack_push_dense(payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, np.float32)


def pack_show_click(ids, shows, clicks) -> bytes:
    return (_LEN.pack(len(ids))
            + np.ascontiguousarray(ids, np.int64).tobytes()
            + np.ascontiguousarray(shows, np.float32).tobytes()
            + np.ascontiguousarray(clicks, np.float32).tobytes())


def unpack_show_click(payload: bytes):
    (n,) = _LEN.unpack_from(payload, 0)
    ids = np.frombuffer(payload, np.int64, n, 8)
    shows = np.frombuffer(payload, np.float32, n, 8 + 8 * n)
    clicks = np.frombuffer(payload, np.float32, n, 8 + 12 * n)
    return ids, shows, clicks


# ---- at-most-once seq ledger ---------------------------------------------

class SeqLedger:
    """Per-client applied-seq set = contiguous floor + sparse extras.

    The pre-durability ledger was a monotonic "last applied seq" per
    client, which silently drops a LOWER seq arriving later — wrong once
    failover exists: seq 35 can be acked by a dying primary (reaching
    the survivor only via WAL handback) while the client has already
    pushed seq 36 to the new primary. The floor+set form applies every
    seq exactly once regardless of arrival order, and compacts back to
    a bare floor as gaps fill. Callers serialize access (`_seq_lock`).
    """

    def __init__(self):
        self._floor: Dict[str, int] = {}
        self._extra: Dict[str, set] = {}

    def seen(self, client: str, seq: int) -> bool:
        return (seq <= self._floor.get(client, 0)
                or seq in self._extra.get(client, ()))

    def record(self, client: str, seq: int) -> bool:
        """Mark (client, seq) applied; False when it already was."""
        floor = self._floor.get(client, 0)
        if seq <= floor:
            return False
        extra = self._extra.setdefault(client, set())
        if seq in extra:
            return False
        extra.add(seq)
        while floor + 1 in extra:       # compact the contiguous prefix
            floor += 1
            extra.discard(floor)
        self._floor[client] = floor
        return True

    def state(self) -> Dict[str, dict]:
        return {c: {"floor": f, "extra": sorted(self._extra.get(c, ()))}
                for c, f in self._floor.items()}

    def load_state(self, state: Dict[str, dict]) -> None:
        self._floor = {c: int(v["floor"]) for c, v in state.items()}
        self._extra = {c: set(int(s) for s in v.get("extra", ()))
                       for c, v in state.items() if v.get("extra")}


# ---- segmented writer ----------------------------------------------------

def _seg_path(dirname: str, start_lsn: int) -> str:
    return os.path.join(dirname, f"wal-{start_lsn:012d}.log")


def _seg_files(dirname: str) -> List[Tuple[int, str]]:
    out = []
    for p in glob.glob(os.path.join(dirname, _SEG_GLOB)):
        try:
            out.append((int(os.path.basename(p)[4:-4]), p))
        except ValueError:
            continue
    return sorted(out)


class WalWriter:
    """Appends CRC-framed records to segment files named by their first
    lsn; rolls to a new segment past `segment_bytes`. Every append is
    flushed so a reader (replication, the monitor CLI) sees it
    immediately; fsync happens on rollover and `sync()` (snapshot),
    trading per-record fsync latency for the snapshot-anchored
    durability window the recovery contract needs."""

    def __init__(self, dirname: str, start_lsn: int = 1,
                 segment_bytes: Optional[int] = None):
        from ...core import flags as _flags
        os.makedirs(dirname, exist_ok=True)
        self.dirname = dirname
        self._next_lsn = int(start_lsn)
        self.segment_bytes = int(segment_bytes if segment_bytes is not None
                                 else float(_flags.flag("ps_wal_segment_mb"))
                                 * (1 << 20))
        self._f = None
        self._f_bytes = 0
        self._open_segment()
        _LIVE_WRITERS.add(self)

    def _open_segment(self):
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        path = _seg_path(self.dirname, self._next_lsn)
        self._f = open(path, "ab")
        self._f_bytes = self._f.tell()

    @property
    def closed(self) -> bool:
        return self._f is None

    @property
    def last_lsn(self) -> int:
        return self._next_lsn - 1

    def append(self, rtype: int, table: str, client: str, seq: int,
               payload: bytes) -> int:
        rec = Record(self._next_lsn, rtype, table, client, seq, payload)
        self.append_record(rec)
        return rec.lsn

    def append_record(self, rec: Record) -> None:
        """Append a pre-built record. A replica tailing the primary uses
        this to persist replicated records under their ORIGINAL lsn, so
        both WALs carry the identical stream."""
        if rec.lsn != self._next_lsn:
            raise ValueError(
                f"wal append out of order: lsn {rec.lsn} != next "
                f"{self._next_lsn}")
        data = encode_record(rec)
        if _faults._ENABLED:
            # a firing `torn` spec persists a truncated record — the
            # replay path must stop at it, never error
            data = _faults.mangle("ps.wal.write", data)
        self._f.write(data)
        self._f.flush()
        self._f_bytes += len(data)
        self._next_lsn = rec.lsn + 1
        if _monitor._ENABLED:
            _monitor.count("ps.wal.appends")
        if self._f_bytes >= self.segment_bytes:
            self._open_segment()

    def sync(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None


def _read_segment(path: str) -> Tuple[List[Record], bool]:
    """All intact records of one segment file; intact=False when the file
    ends in a torn/short/corrupt record (replay stops there)."""
    recs: List[Record] = []
    with open(path, "rb") as f:
        raw = f.read()
    off = 0
    while off < len(raw):
        if off + _REC_HDR.size > len(raw):
            return recs, False
        _, _, _, _, _, plen = _REC_HDR.unpack_from(raw, off)
        end = off + _REC_HDR.size + plen + _CRC32.size
        if plen < 0 or end > len(raw):
            return recs, False
        try:
            recs.append(decode_record(raw[off:end]))
        except ValueError:
            return recs, False
        off = end
    return recs, True


def replay(dirname: str, after_lsn: int = 0,
           max_records: Optional[int] = None,
           count_fallback: bool = True) -> List[Record]:
    """Records with lsn > after_lsn, in lsn order. A torn tail ends the
    stream at the last intact record (counting `ps.wal.fallbacks` when
    `count_fallback` — replication polls pass False, because a reader
    racing a live appender is not a fallback)."""
    out: List[Record] = []
    for _, path in _seg_files(dirname):
        recs, intact = _read_segment(path)
        for r in recs:
            if r.lsn > after_lsn:
                out.append(r)
                if max_records is not None and len(out) >= max_records:
                    return out
        if not intact:
            if count_fallback and _monitor._ENABLED:
                _monitor.count("ps.wal.fallbacks")
            break
    return out


def repair(dirname: str) -> int:
    """Recovery-time WAL repair: truncate every segment ending in a torn
    record back to its intact prefix (a torn record was never applied nor
    ACKed, so dropping it loses nothing durable) — otherwise replay would
    stop at the tear forever and records appended AFTER recovery, in later
    segments, would be unreachable. Returns the highest intact lsn."""
    last = 0
    for _, path in _seg_files(dirname):
        recs, intact = _read_segment(path)
        if recs:
            last = max(last, recs[-1].lsn)
        if not intact:
            good = sum(len(encode_record(r)) for r in recs)
            with open(path, "r+b") as f:
                f.truncate(good)
            # a truncation IS the recovery falling back to the last
            # intact record — same counter as a snapshot-generation
            # fallback, by the acceptance contract
            if _monitor._ENABLED:
                _monitor.count("ps.wal.fallbacks")
            import warnings
            warnings.warn(f"ps wal: torn tail in {os.path.basename(path)}; "
                          f"truncated to the last intact record")
    return last


def oldest_lsn(dirname: str) -> int:
    """First lsn still covered by the retained segment chain (0 = none)."""
    segs = _seg_files(dirname)
    return segs[0][0] if segs else 0


def gc_segments(dirname: str, below_lsn: int) -> List[str]:
    """Drop segments whose EVERY record is < below_lsn (covered by both
    the fallback snapshot generation and every standby's ack)."""
    segs = _seg_files(dirname)
    removed = []
    for i, (start, path) in enumerate(segs):
        nxt = segs[i + 1][0] if i + 1 < len(segs) else None
        if nxt is not None and nxt <= below_lsn:
            try:
                os.remove(path)
                removed.append(path)
            except OSError:
                pass
    return removed


# ---- crash-atomic snapshots (guard/checkpoint.py commit protocol) --------

class Snapshot(NamedTuple):
    version: int
    lsn: int
    ledger: Dict[str, dict]
    tables: Dict[str, tuple]          # name -> (kind, cfg dict)
    arrays: Dict[str, np.ndarray]     # "<table>::<key>" -> array


def _snap_path(dirname: str, version: int) -> str:
    return os.path.join(dirname, f"ps-snap-v{version}.npz")


def save_snapshot(dirname: str, lsn: int, ledger_state: Dict[str, dict],
                  tables: Dict[str, tuple],
                  arrays: Dict[str, np.ndarray]) -> int:
    """Commit one snapshot generation: versioned npz payload via
    `atomic_write`, then the JSON manifest as the commit record (file
    CRC + lsn watermark + ledger + table configs). The previous manifest
    survives as `.bak` and its payload is retained — the corruption
    fallback generation. Returns the new version."""
    os.makedirs(dirname, exist_ok=True)
    mpath = os.path.join(dirname, _MANIFEST)
    prev = _read_json(mpath)
    version = int(prev.get("version", 0)) + 1 if prev else 1
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    data = buf.getvalue()
    manifest = {
        "version": version, "lsn": int(lsn), "ledger": ledger_state,
        "tables": {n: [k, cfg] for n, (k, cfg) in tables.items()},
        "snap_file": os.path.basename(_snap_path(dirname, version)),
        "file_crc": _crc(data),
    }
    atomic_write(_snap_path(dirname, version), data)
    if _faults._ENABLED:
        # deterministic crash point BETWEEN payload and commit: the
        # manifest still references the previous generation
        _faults.check("ps.snapshot.commit")
    if os.path.exists(mpath):
        import shutil
        shutil.copyfile(mpath, mpath + ".bak")
    atomic_write(mpath, json.dumps(manifest).encode())
    # keep current + fallback payloads, GC older generations
    keep = {manifest["snap_file"], prev.get("snap_file", "")}
    for p in glob.glob(os.path.join(dirname, "ps-snap-v*.npz")):
        if os.path.basename(p) not in keep:
            try:
                os.remove(p)
            except OSError:
                pass
    if _monitor._ENABLED:
        _monitor.count("ps.snapshots")
    return version


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _load_one(dirname: str, manifest: dict) -> Snapshot:
    path = os.path.join(dirname, manifest["snap_file"])
    with open(path, "rb") as f:
        raw = f.read()
    if _crc(raw) != manifest["file_crc"]:
        raise ValueError(f"snapshot {path} failed its checksum")
    npz = np.load(io.BytesIO(raw))
    arrays = {k: npz[k] for k in npz.files}
    return Snapshot(int(manifest["version"]), int(manifest["lsn"]),
                    manifest.get("ledger", {}),
                    {n: (kc[0], kc[1])
                     for n, kc in manifest.get("tables", {}).items()},
                    arrays)


def load_snapshot(dirname: str) -> Optional[Snapshot]:
    """Newest intact snapshot generation, or None when no generation is
    loadable (recovery then replays the full WAL from lsn 0). On a
    corrupt current generation, falls back to `.bak`; an orphaned NEWER
    payload than the manifest references (crash between payload and
    commit) also counts `ps.wal.fallbacks` — the durable state fell back
    to the previous committed generation, exactly as designed."""
    mpath = os.path.join(dirname, _MANIFEST)
    manifest = _read_json(mpath)
    if not manifest:
        return None
    version = int(manifest.get("version", 0))
    orphans = [p for p in glob.glob(os.path.join(dirname, "ps-snap-v*.npz"))
               if _snap_version(p) > version]
    if orphans and _monitor._ENABLED:
        _monitor.count("ps.wal.fallbacks")
    try:
        return _load_one(dirname, manifest)
    except (OSError, ValueError, KeyError, Exception) as e:  # noqa: B014
        bak = _read_json(mpath + ".bak")
        if not bak:
            return None
        if _monitor._ENABLED:
            _monitor.count("ps.wal.fallbacks")
        import warnings
        warnings.warn(f"ps snapshot: {e}; falling back to the previous "
                      f"committed generation (v{bak.get('version')})")
        try:
            return _load_one(dirname, bak)
        except (OSError, ValueError, KeyError):
            return None


def _snap_version(path: str) -> int:
    try:
        return int(os.path.basename(path)[len("ps-snap-v"):-len(".npz")])
    except ValueError:
        return -1


# ---- introspection (python -m paddle_tpu.monitor ps <wal-dir>) -----------

def wal_status(dirname: str) -> dict:
    """Offline view of a PS durability directory: snapshot generations,
    the WAL segment chain (with per-segment intactness), and the HA
    side-file (role + replication watermark) when present."""
    mpath = os.path.join(dirname, _MANIFEST)
    manifest = _read_json(mpath)
    bak = _read_json(mpath + ".bak")
    segments = []
    last = 0
    for start, path in _seg_files(dirname):
        recs, intact = _read_segment(path)
        if recs:
            last = max(last, recs[-1].lsn)
        segments.append({
            "file": os.path.basename(path), "start_lsn": start,
            "bytes": os.path.getsize(path), "records": len(recs),
            "last_lsn": recs[-1].lsn if recs else None, "intact": intact,
        })
    doc = {
        "dir": dirname,
        "snapshot": {
            "version": manifest.get("version"),
            "lsn": manifest.get("lsn"),
            "tables": sorted(manifest.get("tables", {})),
            "bak_version": bak.get("version"),
            "bak_lsn": bak.get("lsn"),
        } if manifest else None,
        "segments": segments,
        "last_lsn": last or manifest.get("lsn", 0),
        "ha": _read_json(os.path.join(dirname, "ha-status.json")) or None,
    }
    return doc
