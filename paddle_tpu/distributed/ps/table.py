"""Parameter-server tables: dense + sparse (hash) with server-side optimizers.

Reference parity: `paddle/fluid/distributed/ps/table/common_dense_table.cc:1`
and `common_sparse_table.cc:1` (dense blocks / id->row hash tables with
per-row optimizer state, lazy row creation, save/load).

TPU-native framing: tables are HOST-side (numpy) — the sparse embedding
tier stays on CPU hosts exactly as in the reference; only pulled rows ever
reach the chip. The update rules run vectorized numpy (the server's C++
math role).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np


class _SGDRule:
    def __init__(self, lr=0.01):
        self.lr = lr

    def slots(self, dim):
        return {}

    def apply(self, w, g, slots):
        w -= self.lr * g
        return w


class _AdagradRule:
    def __init__(self, lr=0.01, eps=1e-8):
        self.lr = lr
        self.eps = eps

    def slots(self, dim):
        return {"g2": np.zeros(dim, np.float32)}

    def apply(self, w, g, slots):
        slots["g2"] += g * g
        w -= self.lr * g / (np.sqrt(slots["g2"]) + self.eps)
        return w


_RULES = {"sgd": _SGDRule, "adagrad": _AdagradRule}


class DenseTable:
    """Fixed-shape dense parameter block (common_dense_table role)."""

    def __init__(self, shape, optimizer="sgd", lr=0.01, initializer=None):
        self._lock = threading.Lock()
        rng = np.random.default_rng(0)
        if initializer == "zeros" or initializer is None:
            self.w = np.zeros(shape, np.float32)
        else:
            self.w = rng.normal(0, 0.01, shape).astype(np.float32)
        self._rule = _RULES[optimizer](lr=lr)
        self._slots = self._rule.slots(self.w.shape)

    def pull(self):
        with self._lock:
            return self.w.copy()

    def push(self, grad):
        with self._lock:
            self._rule.apply(self.w, np.asarray(grad, np.float32), self._slots)

    def set(self, value):
        with self._lock:
            self.w[...] = value

    def state(self):
        return {"w": self.w, "slots": self._slots}


class SparseTable:
    """id -> embedding-row hash table with lazy row init and per-row
    optimizer slots (common_sparse_table role)."""

    def __init__(self, dim, optimizer="sgd", lr=0.01, init_std=0.01, seed=0):
        self.dim = dim
        self._lock = threading.Lock()
        self._rows: Dict[int, np.ndarray] = {}
        self._slots: Dict[int, dict] = {}
        self._rule = _RULES[optimizer](lr=lr)
        self._init_std = init_std
        self._rng = np.random.default_rng(seed)

    def _row(self, key: int) -> np.ndarray:
        r = self._rows.get(key)
        if r is None:
            r = self._rng.normal(0, self._init_std, self.dim).astype(np.float32)
            self._rows[key] = r
            self._slots[key] = self._rule.slots(self.dim)
        return r

    def pull(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1)
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            # accumulate duplicate ids before applying (one update per key)
            acc: Dict[int, np.ndarray] = {}
            for i, g in zip(ids, grads):
                k = int(i)
                if k in acc:
                    acc[k] = acc[k] + g
                else:
                    acc[k] = g.copy()
            for k, g in acc.items():
                self._rule.apply(self._row(k), g, self._slots[k])

    def __len__(self):
        return len(self._rows)

    def state(self):
        return {"rows": self._rows, "slots": self._slots}

    def save(self, path):
        # rows AND per-row optimizer slots round-trip (reference sparse
        # tables persist accessor state alongside embeddings)
        with self._lock:
            keys = np.asarray(list(self._rows), np.int64)
            vals = np.stack([self._rows[int(k)] for k in keys]) if len(keys) \
                else np.zeros((0, self.dim), np.float32)
            slot_arrays = {}
            for sname in self._rule.slots(self.dim):
                slot_arrays["slot_" + sname] = np.stack(
                    [self._slots[int(k)][sname] for k in keys]) if len(keys) \
                    else np.zeros((0, self.dim), np.float32)
        np.savez(path, keys=keys, vals=vals, **slot_arrays)

    def load(self, path):
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        snames = [f[5:] for f in data.files if f.startswith("slot_")]
        # decompress each npz member ONCE; store per-row copies so a row
        # update can't pin the whole backing array
        keys, vals = data["keys"], data["vals"]
        slot_data = {s: data["slot_" + s] for s in snames}
        with self._lock:
            for i, k in enumerate(keys):
                k = int(k)
                self._rows[k] = np.array(vals[i], np.float32)
                self._slots[k] = {s: np.array(slot_data[s][i])
                                  for s in snames} or self._rule.slots(self.dim)
