"""Parameter-server tables: dense + sparse (hash) with server-side optimizers.

Reference parity: `paddle/fluid/distributed/ps/table/common_dense_table.cc:1`
and `common_sparse_table.cc:1` (dense blocks / id->row hash tables with
per-row optimizer state, lazy row creation, save/load).

TPU-native framing: tables are HOST-side (numpy) — the sparse embedding
tier stays on CPU hosts exactly as in the reference; only pulled rows ever
reach the chip. The update rules run vectorized numpy (the server's C++
math role).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np


class _SGDRule:
    def __init__(self, lr=0.01, **_unused_hyper):
        self.lr = lr

    def slots(self, dim):
        return {}

    def apply(self, w, g, slots):
        w -= self.lr * g
        return w


class _AdagradRule:
    def __init__(self, lr=0.01, eps=1e-8, **_unused_hyper):
        self.lr = lr
        self.eps = eps

    def slots(self, dim):
        return {"g2": np.zeros(dim, np.float32)}

    def apply(self, w, g, slots):
        slots["g2"] += g * g
        w -= self.lr * g / (np.sqrt(slots["g2"]) + self.eps)
        return w


class _AdamRule:
    """Dense/sparse adam with per-row moments and per-row step counter
    (adam_op.h dense path / common_sparse_table adam accessor)."""

    def __init__(self, lr=0.01, beta1=0.9, beta2=0.999, eps=1e-8,
                 **_unused_hyper):
        self.lr, self.b1, self.b2, self.eps = lr, beta1, beta2, eps

    def slots(self, dim):
        return {"m": np.zeros(dim, np.float32),
                "v": np.zeros(dim, np.float32),
                "t": np.zeros((), np.float32)}

    def apply(self, w, g, slots):
        slots["t"] += 1.0
        t = float(slots["t"])
        slots["m"][...] = self.b1 * slots["m"] + (1 - self.b1) * g
        slots["v"][...] = self.b2 * slots["v"] + (1 - self.b2) * g * g
        mhat = slots["m"] / (1 - self.b1 ** t)
        vhat = slots["v"] / (1 - self.b2 ** t)
        w -= self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return w


# lazy adam IS per-row adam on a sparse table: moments advance only when a
# row receives a gradient (reference lazy_mode; common_sparse_table.cc:1) —
# the SparseTable's per-key slot storage gives that behavior for free
_RULES = {"sgd": _SGDRule, "adagrad": _AdagradRule, "adam": _AdamRule,
          "lazy_adam": _AdamRule}

# wire ids for the table-config negotiation frames (service.py cmds 10/11
# and the native plane's config structs) — the ONE mapping both planes and
# both table kinds share
OPT_WIRE_IDS = {"sgd": 0, "adagrad": 1, "adam": 2, "lazy_adam": 2}


class CtrAccessor:
    """Show/click statistics + eviction scoring per sparse row.

    Reference parity: `paddle/fluid/distributed/ps/table/ctr_accessor.cc`
    (CtrCommonAccessor): every row carries decayed show/click counters; the
    shrink pass evicts rows whose score falls below a threshold or that
    have not been seen for `ttl_days` decay cycles.
    """

    def __init__(self, show_decay_rate=0.98, click_coeff=8.0,
                 delete_threshold=0.8, ttl_days=30):
        self.show_decay_rate = show_decay_rate
        self.click_coeff = click_coeff
        self.delete_threshold = delete_threshold
        self.ttl_days = ttl_days

    def fresh(self):
        return {"show": 0.0, "click": 0.0, "unseen_days": 0.0}

    def on_show_click(self, stat, show, click):
        stat["show"] += float(show)
        stat["click"] += float(click)
        stat["unseen_days"] = 0.0

    def decay(self, stat):
        """One decay cycle (reference UpdateTimeDecay, daily)."""
        stat["show"] *= self.show_decay_rate
        stat["click"] *= self.show_decay_rate
        stat["unseen_days"] += 1.0

    def score(self, stat):
        return stat["show"] + self.click_coeff * stat["click"]

    def should_evict(self, stat):
        return (self.score(stat) < self.delete_threshold
                or stat["unseen_days"] > self.ttl_days)


def dense_shard_range(total: int, shard: int, n_shards: int):
    """Contiguous row-range partition of a flat dense block (reference
    `ps/table/common_dense_table.cc` fixed_len split): shard s holds
    [start, end) with the remainder spread over the leading shards."""
    base, rem = divmod(int(total), int(n_shards))
    start = shard * base + min(shard, rem)
    return start, start + base + (1 if shard < rem else 0)


class DenseTable:
    """Fixed-shape dense parameter block (common_dense_table role). With
    `shard=(i, n)` the table holds only its contiguous row-range slice of
    the flattened block — the reference distributes dense params across
    servers the same way (`common_dense_table.cc`), removing the
    server-0 bandwidth/memory pinch point."""

    def __init__(self, shape, optimizer="sgd", lr=0.01, initializer=None,
                 shard=None, beta1=0.9, beta2=0.999, eps=1e-8,
                 shard_lo=None, total_size=None):
        self._lock = threading.Lock()
        total = int(np.prod(shape))
        self.total_size = total
        if shard_lo is not None:
            # explicit range (wire-negotiated tables): must be set BEFORE
            # the initializer so per-shard RNG streams decorrelate by the
            # TRUE global offset, not a post-construction patch
            self.total_size = int(total_size) if total_size else total
            self.shard_range = (int(shard_lo), int(shard_lo) + total)
            myshape = tuple(shape)
        elif shard is not None:
            i, n = shard
            if not 0 <= i < n:
                raise ValueError(f"dense shard index {i} out of range for "
                                 f"{n} shards")
            lo, hi = dense_shard_range(total, i, n)
            myshape: tuple = (hi - lo,)
            self.shard_range = (lo, hi)
        else:
            myshape = tuple(shape)
            self.shard_range = (0, total)
        if initializer == "zeros" or initializer is None:
            self.w = np.zeros(myshape, np.float32)
        else:
            # seed by the global offset so different shards draw
            # decorrelated streams
            rng = np.random.default_rng(self.shard_range[0])
            self.w = rng.normal(0, 0.01, myshape).astype(np.float32)
        self._rule = _RULES[optimizer](lr=lr, beta1=beta1, beta2=beta2,
                                       eps=eps)
        self._slots = self._rule.slots(self.w.shape)

    def pull(self):
        with self._lock:
            return self.w.copy()

    def push(self, grad):
        with self._lock:
            self._rule.apply(self.w, np.asarray(grad, np.float32), self._slots)

    def set(self, value):
        with self._lock:
            self.w[...] = value

    def state(self):
        return {"w": self.w, "slots": self._slots}

    def snapshot_arrays(self):
        """Durable state as flat arrays (see SparseTable.snapshot_arrays)."""
        with self._lock:
            out = {"w": self.w.copy()}
            for sname, arr in self._slots.items():
                out["slot_" + sname] = np.array(arr)
        return out

    def load_arrays(self, data):
        names = getattr(data, "files", None)
        if names is None:
            names = list(data.keys())
        with self._lock:
            self.w[...] = data["w"]
            for f in names:
                if f.startswith("slot_"):
                    # keep 0-d slots 0-d (adam's step counter "t")
                    self._slots[f[5:]] = np.array(data[f], np.float32)


class SparseTable:
    """id -> embedding-row hash table with lazy row init and per-row
    optimizer slots (common_sparse_table role)."""

    def __init__(self, dim, optimizer="sgd", lr=0.01, init_std=0.01, seed=0,
                 accessor=None, beta1=0.9, beta2=0.999, eps=1e-8,
                 **accessor_kw):
        self.dim = dim
        self._lock = threading.Lock()
        self._rows: Dict[int, np.ndarray] = {}
        self._slots: Dict[int, dict] = {}
        self._rule = _RULES[optimizer](lr=lr, beta1=beta1, beta2=beta2,
                                       eps=eps)
        self._init_std = init_std
        self._seed = seed
        # accessor="ctr": per-row show/click stats + decay/shrink eviction
        if accessor not in (None, "ctr"):
            raise TypeError(f"unknown accessor {accessor!r}")
        if accessor is None and accessor_kw:
            raise TypeError(
                f"unexpected keyword arguments {sorted(accessor_kw)} "
                "(accessor options need accessor='ctr')")
        self._accessor = CtrAccessor(**accessor_kw) if accessor == "ctr" else None
        self._stats: Dict[int, dict] = {}
        # keys evicted by the most recent shrink() — the delta-push plane
        # reads this to ship tombstones to serving subscribers
        self.last_shrink_evicted: list = []

    def _row(self, key: int) -> np.ndarray:
        r = self._rows.get(key)
        if r is None:
            # keyed (seed, id) stream, NOT a shared table RNG: lazy init
            # must not depend on first-touch ORDER, or a replica replaying
            # the same deltas in a different interleaving (WAL replay,
            # standby tail) would diverge from the primary
            rng = np.random.default_rng((self._seed, key & 0x7FFFFFFFFFFFFFFF))
            r = rng.normal(0, self._init_std, self.dim).astype(np.float32)
            self._rows[key] = r
            self._slots[key] = self._rule.slots(self.dim)
            if self._accessor is not None:
                self._stats[key] = self._accessor.fresh()
        return r

    # ---- CTR accessor surface (ctr_accessor.cc role) ----
    def push_show_click(self, ids, shows, clicks):
        if self._accessor is None:
            raise ValueError("table has no ctr accessor")
        ids = np.asarray(ids).reshape(-1)
        shows = np.asarray(shows).reshape(-1)
        clicks = np.asarray(clicks).reshape(-1)
        with self._lock:
            for i, s, c in zip(ids, shows, clicks):
                self._row(int(i))
                self._accessor.on_show_click(self._stats[int(i)], s, c)

    def decay(self):
        """One show/click decay cycle over every row (daily shrink prep)."""
        if self._accessor is None:
            raise ValueError("table has no ctr accessor")
        with self._lock:
            for st in self._stats.values():
                self._accessor.decay(st)

    def _on_evict(self, key):
        """Hook for subclasses tracking rows outside _rows (SSD tier)."""

    def shrink(self):
        """Evict rows below the score threshold or past their TTL
        (reference Table::Shrink). Returns number of evicted rows."""
        if self._accessor is None:
            raise ValueError("table has no ctr accessor")
        with self._lock:
            dead = [k for k, st in self._stats.items()
                    if self._accessor.should_evict(st)]
            for k in dead:
                self._rows.pop(k, None)
                self._slots.pop(k, None)
                self._stats.pop(k, None)
                self._on_evict(k)
            # evicted keys from the LAST shrink, for consumers that must
            # propagate tombstones (the PS delta-push plane)
            self.last_shrink_evicted = list(dead)
            return len(dead)

    def row_stat(self, key: int) -> Optional[dict]:
        with self._lock:
            st = self._stats.get(int(key))
            return dict(st) if st is not None else None

    def pull(self, ids) -> np.ndarray:
        ids = np.asarray(ids).reshape(-1)
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            # accumulate duplicate ids before applying (one update per key)
            acc: Dict[int, np.ndarray] = {}
            for i, g in zip(ids, grads):
                k = int(i)
                if k in acc:
                    acc[k] = acc[k] + g
                else:
                    acc[k] = g.copy()
            for k, g in acc.items():
                self._rule.apply(self._row(k), g, self._slots[k])

    def __len__(self):
        return len(self._rows)

    def state(self):
        return {"rows": self._rows, "slots": self._slots}

    _STAT_FIELDS = ("show", "click", "unseen_days")

    def _iter_all_rows(self):
        """(key, row, slots, stat) for every row the table owns — the SSD
        tier overrides this to include spilled rows."""
        for k in self._rows:
            yield k, self._rows[k], self._slots[k], self._stats.get(k)

    def snapshot_arrays(self):
        """Complete durable state as flat arrays — rows, per-row optimizer
        slots AND accessor stats round-trip (reference sparse tables
        persist accessor state with embeddings). Shared by `save` and the
        PS snapshot plane (`wal.save_snapshot`)."""
        with self._lock:
            items = list(self._iter_all_rows())
            keys = np.asarray([k for k, *_ in items], np.int64)
            vals = np.stack([r for _, r, _, _ in items]) if items \
                else np.zeros((0, self.dim), np.float32)
            slot_arrays = {}
            for sname in self._rule.slots(self.dim):
                slot_arrays["slot_" + sname] = np.stack(
                    [s[sname] for _, _, s, _ in items]) if items \
                    else np.zeros((0, self.dim), np.float32)
            if self._accessor is not None:
                for f in self._STAT_FIELDS:
                    # float64: stats are Python floats in memory, and the
                    # durability contract is a BIT-EXACT round-trip
                    slot_arrays["stat_" + f] = np.asarray(
                        [(st or self._accessor.fresh())[f]
                         for _, _, _, st in items], np.float64)
        return dict(keys=keys, vals=vals, **slot_arrays)

    def save(self, path):
        np.savez(path, **self.snapshot_arrays())

    def load_arrays(self, data):
        """Install state from a `snapshot_arrays`-shaped mapping (a dict
        of arrays or an open npz)."""
        names = getattr(data, "files", None)
        if names is None:
            names = list(data.keys())
        snames = [f[5:] for f in names if f.startswith("slot_")]
        has_stats = "stat_show" in names
        # decompress each npz member ONCE; store per-row copies so a row
        # update can't pin the whole backing array
        keys, vals = data["keys"], data["vals"]
        slot_data = {s: data["slot_" + s] for s in snames}
        stat_data = {f: data["stat_" + f] for f in self._STAT_FIELDS} \
            if has_stats else None
        with self._lock:
            for i, k in enumerate(keys):
                k = int(k)
                self._rows[k] = np.array(vals[i], np.float32)
                self._slots[k] = {s: np.array(slot_data[s][i])
                                  for s in snames} or self._rule.slots(self.dim)
                if self._accessor is not None:
                    self._stats[k] = (
                        {f: float(stat_data[f][i]) for f in self._STAT_FIELDS}
                        if stat_data is not None else self._accessor.fresh())
                self._on_load_row(k)

    def load(self, path):
        self.load_arrays(
            np.load(path if path.endswith(".npz") else path + ".npz"))

    def _on_load_row(self, key):
        """Hook: SSD tier registers loaded rows in its LRU and spills."""


class SSDSparseTable(SparseTable):
    """Sparse table with a bounded in-memory working set; cold rows spill
    to an on-disk key-value store and reload transparently on access.

    Reference parity: `paddle/fluid/distributed/ps/table/ssd_sparse_table.h`
    (RocksDB-backed sparse tier for embedding tables larger than RAM). The
    disk store here is a stdlib dbm database holding pickled (row, slots,
    stat) triples; eviction is LRU over the in-memory dict.
    """

    def __init__(self, dim, path, cache_rows=100000, **kw):
        super().__init__(dim, **kw)
        import dbm
        import os as _os
        _os.makedirs(_os.path.dirname(_os.path.abspath(path)) or ".",
                     exist_ok=True)
        self._db = dbm.open(path, "c")
        self._cache_rows = int(cache_rows)
        self._lru: Dict[int, None] = {}  # insertion-ordered LRU

    def _touch(self, key):
        self._lru.pop(key, None)
        self._lru[key] = None

    def _spill_if_needed(self):
        import pickle
        while len(self._rows) > self._cache_rows and self._lru:
            cold = next(iter(self._lru))
            self._lru.pop(cold)
            if cold not in self._rows:  # evicted by shrink since touched
                continue
            blob = pickle.dumps((self._rows.pop(cold),
                                 self._slots.pop(cold),
                                 self._stats.pop(cold, None)))
            self._db[str(cold).encode()] = blob

    def _row(self, key: int) -> np.ndarray:
        r = self._rows.get(key)
        if r is None:
            import pickle
            blob = self._db.get(str(key).encode())
            if blob is not None:
                row, slots, stat = pickle.loads(blob)
                self._rows[key] = row
                self._slots[key] = slots
                if stat is not None:
                    self._stats[key] = stat
                del self._db[str(key).encode()]
                r = row
            else:
                r = super()._row(key)
        self._touch(key)
        self._spill_if_needed()
        return r

    def __len__(self):
        # resident + spilled
        with self._lock:
            return len(self._rows) + len(self._db)

    @property
    def resident_rows(self):
        with self._lock:
            return len(self._rows)

    # ---- hooks keeping the LRU/disk tiers consistent with the base ----
    def _on_evict(self, key):
        self._lru.pop(key, None)
        k = str(key).encode()
        if k in self._db:
            del self._db[k]

    def _on_load_row(self, key):
        # A load() into a reused spill db must supersede any stale disk
        # copy, or _iter_all_rows would yield the key twice and the stale
        # row would win on the next load.
        self._on_evict(key)
        self._touch(key)
        self._spill_if_needed()

    def _iter_all_rows(self):
        import pickle
        yield from super()._iter_all_rows()
        for kb in self._db.keys():
            if int(kb.decode()) in self._rows:
                continue  # resident copy is authoritative
            row, slots, stat = pickle.loads(self._db[kb])
            yield int(kb.decode()), row, slots, stat

    def decay(self):
        """Decay covers SPILLED rows too (rewrites their stat on disk)."""
        super().decay()
        if self._accessor is None:
            return
        import pickle
        with self._lock:
            for kb in list(self._db.keys()):
                row, slots, stat = pickle.loads(self._db[kb])
                if stat is not None:
                    self._accessor.decay(stat)
                    self._db[kb] = pickle.dumps((row, slots, stat))

    def shrink(self):
        """Shrink walks the disk tier as well — the coldest rows are
        exactly the ones most likely to be spilled."""
        n = super().shrink()
        if self._accessor is None:
            return n
        import pickle
        with self._lock:
            for kb in list(self._db.keys()):
                _, _, stat = pickle.loads(self._db[kb])
                if stat is not None and self._accessor.should_evict(stat):
                    del self._db[kb]
                    self.last_shrink_evicted.append(int(kb))
                    n += 1
        return n

    def close(self):
        self._db.close()
