"""PS high availability: warm-standby replication + lease failover.

Reference parity: industrial PS deployments pair each brpc PS shard
group with warm standbys that tail the primary's delta stream and take
over on failure. Here the same roles are built from the repo's own
primitives: the rendezvous TCPStore (namespaced via
`elastic.PrefixStore`) holds the primary record and the lease plane
(`ElasticManager`), the delta stream is the server's WAL served over
CMD_REPLICATE, and promotion is an epoch-numbered claim — the highest
epoch in the store wins, so a promotion race converges without a
consensus protocol.

Topology: one `HaPsNode` per process wraps one `PsServer`. The primary
serves trainers; each standby tails the primary's WAL (acking its
applied watermark), and promotes itself when the primary's lease
expires. `PsClient(resolver=ha.resolver(store))` re-reads the primary
record inside its retry loop, so a trainer fails over within its
original per-call deadline; in-flight pushes replay idempotently off
the replicated seq ledger. A recovered ex-primary REJOINS as the new
standby: it replays its own WAL, hands the new primary any records the
replication tail missed (CMD_HANDBACK, ledger-dedup'd), then re-anchors
on the new primary's state (CMD_FETCH_STATE) and starts tailing.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import List, Optional

from . import wal as _wal
from .service import (PsClient, PsError, PsServer, ha_connect,
                      rpc_fetch_state, rpc_ha_status, rpc_handback,
                      rpc_replicate)
from ... import monitor as _monitor
from ...core import flags as _flags
from ...framework.sharded_io import atomic_write
from ...parallel.elastic import ElasticManager, PrefixStore
from ...utils import syncwatch as _syncwatch

__all__ = ["HaPsNode", "resolver", "connect"]

# ranks an HA group's lease watcher scans (one PS group never has more
# nodes than this; alive_ranks iterates the range)
_MAX_NODES = 16

# live HaPsNode instances, for the conftest leak guard (`_no_ps_leak`)
_LIVE = weakref.WeakSet()


def _read_json(store, key) -> Optional[dict]:
    try:
        return json.loads(store.get(key).decode())
    except (KeyError, ValueError):
        return None


def resolver(store, name: str = "ps"):
    """Endpoint resolver for `PsClient`: re-reads the current primary
    record from the rendezvous store on every call."""
    ns = PrefixStore(store, f"ps:{name}:")

    def _resolve() -> List[str]:
        rec = _read_json(ns, "primary")
        if not rec:
            return []
        return [f"{rec['host']}:{rec['port']}"]

    return _resolve


def connect(store, name: str = "ps", **kw) -> PsClient:
    """A PsClient bound to the HA group's CURRENT primary, failing over
    through the store on transport errors."""
    return PsClient(resolver=resolver(store, name), **kw)


class HaPsNode:
    """One member of an HA parameter-server group (primary or standby).

    `start()` claims the primary role if the record is absent or its
    lease is dead, otherwise bootstraps as a standby (handback + state
    fetch + replication tail). The node heartbeats its lease either way;
    a standby promotes itself on the primary's lease-expiry transition.
    """

    def __init__(self, store, name: str = "ps",
                 wal_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 lease_ttl: Optional[float] = None,
                 heartbeat: Optional[float] = None):
        self._ns = PrefixStore(store, f"ps:{name}:")
        self.name = name
        self.node_id = int(self._ns.add("next_id", 1)) - 1
        self.server = PsServer(host, port, wal_dir=wal_dir)
        self.lease_ttl = float(_flags.flag("ps_ha_lease_ttl_s")
                               if lease_ttl is None else lease_ttl)
        self.heartbeat = float(_flags.flag("ps_ha_heartbeat_s")
                               if heartbeat is None else heartbeat)
        self.role: Optional[str] = None
        self.epoch = 0
        self._primary_rec: Optional[dict] = None
        self._es = ElasticManager(self._ns, rank=self.node_id,
                                  world_size=_MAX_NODES,
                                  lease_ttl=self.lease_ttl,
                                  heartbeat_interval=self.heartbeat)
        self._loop_stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._repl_sock = None
        self._status_written = 0.0
        self._promote_lock = threading.Lock()
        self._closed = False
        _LIVE.add(self)

    # ---- lifecycle ----

    def start(self) -> "HaPsNode":
        self.server.run()
        self._ns.set(f"node:{self.node_id}",
                     json.dumps({"host": self.server.host,
                                 "port": self.server.port}))
        self._es.register()
        rec = _read_json(self._ns, "primary")
        alive = (rec is not None
                 and rec.get("rank") in self._es.alive_ranks())
        if alive:
            self._become_standby(rec)
        else:
            self._claim_primary()
        # one maintenance thread for both roles: a standby tails the
        # primary's delta stream; both roles keep ha-status.json fresh
        self._loop_thread = _syncwatch.Thread(
            target=self._loop, daemon=True, name="ps-repl-tail")
        self._loop_thread.start()
        return self

    def stop(self):
        self._loop_stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None
        if self._repl_sock is not None:
            try:
                self._repl_sock.close()
            except OSError:
                pass
            self._repl_sock = None
        self._es.stop()
        self._write_status(force=True)
        self.server.stop()
        self._closed = True

    @property
    def endpoint(self) -> str:
        return f"{self.server.host}:{self.server.port}"

    # ---- role management ----

    def _claim_primary(self):
        """Epoch-numbered claim: take the next epoch and publish the
        record; if a concurrent claimant published a HIGHER epoch, yield
        to it (converges without consensus)."""
        self.epoch = int(self._ns.add("primary_epoch", 1))
        self._ns.set("primary", json.dumps({
            "rank": self.node_id, "host": self.server.host,
            "port": self.server.port, "epoch": self.epoch}))
        cur = _read_json(self._ns, "primary") or {}
        if int(cur.get("epoch", 0)) > self.epoch:
            return self._become_standby(cur)
        self.role = self.server.ha_role = "primary"
        self._primary_rec = None
        from ...obs import telemetry as _telemetry
        _telemetry.emit("role_change", role="primary", node=self.node_id,
                        epoch=self.epoch)
        self._write_status(force=True)

    def _become_standby(self, rec: dict):
        self.role = self.server.ha_role = "standby"
        self._primary_rec = rec
        from ...obs import telemetry as _telemetry
        _telemetry.emit("role_change", role="standby", node=self.node_id,
                        primary=rec.get("rank"))
        endpoint = f"{rec['host']}:{rec['port']}"
        sk = ha_connect(endpoint)
        try:
            if self.server.applied_lsn > 0 and self.server.wal_dir:
                # rejoining ex-primary: hand over WAL records the new
                # primary's replication tail never saw (ledger dedups)
                st = rpc_ha_status(sk)
                floor = int(st.get("handback_floor", 0))
                recs = _wal.replay(self.server.wal_dir, after_lsn=floor,
                                   count_fallback=False)
                if recs:
                    rpc_handback(sk, recs)
            # re-anchor the local durability chain on the primary's state
            self.server.reset_state()
            meta, blob = rpc_fetch_state(sk)
            self.server.install_state(meta, blob)
        finally:
            sk.close()
        # promote on the primary's lease-expiry transition (fires once;
        # re-registration for later epochs re-arms in ElasticManager)
        self._es.on_rank_dead(self._on_rank_dead,
                              interval=min(0.2, self.heartbeat))
        self._write_status(force=True)

    def _on_rank_dead(self, rank: int):
        rec = self._primary_rec
        if self.role == "standby" and rec and rank == rec.get("rank"):
            self.promote()

    def promote(self):
        """Standby -> primary: freeze the handback floor at what the
        replication tail applied, then claim the next epoch."""
        with self._promote_lock:
            if self.role != "standby":
                return
            self.server._handback_floor = self.server.applied_lsn
            self._claim_primary()
            if _monitor._ENABLED:
                _monitor.count("ps.promotions")

    # ---- maintenance loop ----

    def _loop(self):
        interval = float(_flags.flag("ps_replication_interval_ms")) / 1e3
        while not self._loop_stop.wait(interval):
            if self.role == "standby":
                self._tail_once()
            self._write_status()

    def _tail_once(self):
        rec = self._primary_rec
        if rec is None:
            return
        try:
            if self._repl_sock is None:
                self._repl_sock = ha_connect(f"{rec['host']}:{rec['port']}")
            recs = rpc_replicate(self._repl_sock,
                                 after_lsn=self.server.applied_lsn,
                                 standby_id=str(self.node_id))
            for r in recs:
                self.server.apply_replicated(r)
        except (OSError, PsError, ValueError):
            # primary unreachable: drop the socket and let the lease
            # watcher decide about promotion
            if self._repl_sock is not None:
                try:
                    self._repl_sock.close()
                except OSError:
                    pass
                self._repl_sock = None

    def _write_status(self, force: bool = False):
        """Side-file for `python -m paddle_tpu.monitor ps <wal-dir>`:
        the offline renderer's view of role + replication watermark."""
        if self.server.wal_dir is None:
            return
        now = time.monotonic()
        if not force and now - self._status_written < 0.2:
            return
        self._status_written = now
        doc = {"role": self.role, "node_id": self.node_id,
               "epoch": self.epoch, "applied_lsn": self.server.applied_lsn,
               "acks": dict(self.server._repl_acks),
               "endpoint": self.endpoint}
        try:
            atomic_write(os.path.join(self.server.wal_dir, "ha-status.json"),
                         json.dumps(doc).encode())
        except OSError:
            pass
