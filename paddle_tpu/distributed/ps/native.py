"""Native (C++) PS server facade.

Reference parity: `ps/service/brpc_ps_server.cc` — the reference's PS data
plane is native C++; this exposes `csrc/ps_server.cpp` (same wire protocol
as the python `PsServer`) through the ctypes bridge. A cluster may mix
python and native servers; the python `PsClient` drives both unchanged.

Scope: the high-QPS data plane (SGD sparse/dense tables, barrier, error
frames). Rich table features — adam/adagrad slots, CTR accessor, TTL
shrink, SSD spill, save/load — live in the python tier (`service.PsServer`),
which remains the full-featured server.
"""
from __future__ import annotations

import ctypes

from ... import _native
from .table import dense_shard_range


class NativePsServer:
    """C++ parameter server bound to 127.0.0.1:<port> (0 = ephemeral)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        if host not in ("127.0.0.1", "localhost"):
            raise ValueError(
                "NativePsServer binds loopback only for now; front a "
                f"non-loopback host ({host!r}) with the python PsServer")
        lib = _native._load()
        if not lib:
            raise RuntimeError(
                "native PS server requires the C++ toolchain (g++); "
                "use distributed.ps.PsServer (python) instead")
        self._lib = lib
        import threading
        self._stopped = threading.Event()
        out_port = ctypes.c_int(0)
        self._h = lib.ps_native_server_start(int(port),
                                             ctypes.byref(out_port))
        if not self._h:
            raise RuntimeError("native PS server failed to bind")
        self.host = host
        self.port = int(out_port.value)

    def add_sparse_table(self, name: str, dim: int, lr: float = 0.01,
                         init_std: float = 0.01, seed: int = 0,
                         optimizer: str = "sgd"):
        if optimizer != "sgd":
            raise NotImplementedError(
                "the native data plane ships SGD tables; richer optimizers "
                "live in the python PsServer")
        rc = self._lib.ps_native_add_sparse(
            self._h, name.encode(), int(dim), float(lr), float(init_std),
            int(seed))
        if rc == -2:
            raise ValueError(f"table {name!r} already registered")
        if rc != 0:
            raise ValueError(f"add_sparse_table({name!r}) failed")

    def add_dense_table(self, name: str, shape, lr: float = 0.01,
                        shard=None, optimizer: str = "sgd"):
        if optimizer != "sgd":
            raise NotImplementedError(
                "the native data plane ships SGD tables; richer optimizers "
                "live in the python PsServer")
        import numpy as np
        total = int(np.prod(shape))
        if shard is not None:
            i, n = shard
            if not 0 <= i < n:
                raise ValueError(f"dense shard index {i} out of range for "
                                 f"{n} shards")
            lo, hi = dense_shard_range(total, i, n)
        else:
            lo, hi = 0, total
        rc = self._lib.ps_native_add_dense(
            self._h, name.encode(), hi - lo, float(lr), lo, total)
        if rc == -2:
            raise ValueError(f"table {name!r} already registered")
        if rc != 0:
            raise ValueError(f"add_dense_table({name!r}) failed")

    def run(self, block: bool = False):
        # the accept loop starts at construction; block=True keeps the
        # caller alive until stop() (python PsServer.run parity)
        if block:
            self._stopped.wait()
        return self

    def stop(self):
        if self._h:
            self._lib.ps_native_server_stop(self._h)
            self._h = None
        self._stopped.set()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass
