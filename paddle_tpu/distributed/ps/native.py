"""Native (C++) PS server facade.

Reference parity: `ps/service/brpc_ps_server.cc` — the reference's PS data
plane is native C++; this exposes `csrc/ps_server.cpp` (same wire protocol
as the python `PsServer`) through the ctypes bridge. A cluster may mix
python and native servers; the python `PsClient` drives both unchanged.

Scope: the high-QPS data plane — sgd/adagrad/adam sparse+dense tables
with per-row optimizer slots, the CTR accessor (show/click stats, time
decay, TTL/score shrink), barrier, error frames, and remote table-config
negotiation. SSD spill and save/load remain python-tier features
(`service.PsServer`).
"""
from __future__ import annotations

import ctypes

from ... import _native
from .table import dense_shard_range


class NativePsServer:
    """C++ parameter server bound to 127.0.0.1:<port> (0 = ephemeral)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        # teardown-safe defaults FIRST: __del__ runs even when __init__
        # raises (no toolchain, bind failure), so every attribute stop()
        # touches must already exist
        import threading
        self._h = None
        self._lib = None
        self._stopped = threading.Event()
        if host not in ("127.0.0.1", "localhost"):
            raise ValueError(
                "NativePsServer binds loopback only for now; front a "
                f"non-loopback host ({host!r}) with the python PsServer")
        lib = _native._load()
        if not lib:
            raise RuntimeError(
                "native PS server requires the C++ toolchain (g++); "
                "use distributed.ps.PsServer (python) instead")
        self._lib = lib
        out_port = ctypes.c_int(0)
        self._h = lib.ps_native_server_start(int(port),
                                             ctypes.byref(out_port))
        if not self._h:
            raise RuntimeError("native PS server failed to bind")
        self.host = host
        self.port = int(out_port.value)

    def add_sparse_table(self, name: str, dim: int, lr: float = 0.01,
                         init_std: float = 0.01, seed: int = 0,
                         optimizer: str = "sgd", accessor=None,
                         beta1: float = 0.9, beta2: float = 0.999,
                         eps: float = 1e-8, show_decay_rate: float = 0.98,
                         click_coeff: float = 8.0,
                         delete_threshold: float = 0.8,
                         ttl_days: float = 30.0):
        from .table import OPT_WIRE_IDS as opt_ids
        if optimizer not in opt_ids:
            raise NotImplementedError(
                f"native PS optimizer {optimizer!r} (have {sorted(opt_ids)})")
        if accessor not in (None, "ctr"):
            raise TypeError(f"unknown accessor {accessor!r}")
        rc = self._lib.ps_native_add_sparse_v2(
            self._h, name.encode(), int(dim), float(lr), float(init_std),
            int(seed), opt_ids[optimizer], float(beta1), float(beta2),
            float(eps), 1 if accessor == "ctr" else 0,
            float(show_decay_rate), float(click_coeff),
            float(delete_threshold), float(ttl_days))
        if rc == -2:
            raise ValueError(f"table {name!r} already registered")
        if rc != 0:
            raise ValueError(f"add_sparse_table({name!r}) failed")

    def add_dense_table(self, name: str, shape, lr: float = 0.01,
                        shard=None, optimizer: str = "sgd",
                        beta1: float = 0.9, beta2: float = 0.999,
                        eps: float = 1e-8):
        from .table import OPT_WIRE_IDS as opt_ids
        if optimizer not in opt_ids:
            raise NotImplementedError(
                f"native PS optimizer {optimizer!r} (have {sorted(opt_ids)})")
        import numpy as np
        total = int(np.prod(shape))
        if shard is not None:
            i, n = shard
            if not 0 <= i < n:
                raise ValueError(f"dense shard index {i} out of range for "
                                 f"{n} shards")
            lo, hi = dense_shard_range(total, i, n)
        else:
            lo, hi = 0, total
        rc = self._lib.ps_native_add_dense_v2(
            self._h, name.encode(), hi - lo, float(lr), lo, total,
            opt_ids[optimizer], float(beta1), float(beta2), float(eps))
        if rc == -2:
            raise ValueError(f"table {name!r} already registered")
        if rc != 0:
            raise ValueError(f"add_dense_table({name!r}) failed")

    def run(self, block: bool = False):
        # the accept loop starts at construction; block=True keeps the
        # caller alive until stop() (python PsServer.run parity)
        if block:
            self._stopped.wait()
        return self

    def stop(self):
        # shutdown-before-close (PsServer.stop() ordering): wake blocked
        # run() callers BEFORE the native handle is freed, so none of
        # them can observe a half-torn-down server
        self._stopped.set()
        h, self._h = self._h, None
        if h and self._lib is not None:
            self._lib.ps_native_server_stop(h)

    def __del__(self):
        try:
            if getattr(self, "_h", None) is not None:
                self.stop()
        except Exception:
            pass
