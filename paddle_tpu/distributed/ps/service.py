"""PS RPC plane: threaded socket server + sharded client + async Communicator.

Reference parity: `ps/service/brpc_ps_client.h` / `brpc_ps_server.cc`
(pull/push dense+sparse RPCs), `ps/service/communicator/communicator.cc:1`
(async grad send batching), proto `sendrecv.proto`.

Redesign: brpc is replaced by a length-prefixed binary protocol over raw
sockets (the C++ TCPStore's wire style) — request header `cmd table n dim`
+ raw little-endian buffers, no pickle on the hot path. Every response
starts with a one-byte status; errors carry a message frame so server-side
failures (unknown table, barrier timeout) surface to the caller instead of
tearing the connection down. Sparse tables shard across servers by
`id % n_servers`; dense tables are row-range sharded across all
servers. Shard RPCs are issued
send-first-then-receive so a pull touches all servers in ~one RTT (the
brpc client's concurrent-request role).
"""
from __future__ import annotations

import json
import os
import random
import socket
import struct
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from . import wal as _wal
from .table import DenseTable, SparseTable
from ... import faults as _faults
from ... import monitor as _monitor
from ...core import flags as _flags
from ...obs import trace as _trace

# The PS codec reads/writes CMD_* frames on connections the substrate
# (utils/net.py RpcChannel / secure_server / dial) owns and hands out —
# those raw send/recv calls are the plane's wire format, not a bypass.
# tpu-lint: disable=raw-socket

_HDR = struct.Struct("<B16sqq")  # cmd, table name (padded), n, dim
# payload plausibility caps (the header fields are client-controlled)
_MAX_PAYLOAD_ROWS = 1 << 24      # 16M ids per request
_MAX_PAYLOAD_DIM = 1 << 16       # 64K embedding width
_MAX_PAYLOAD_ELEMS = 1 << 28     # 256M f32 elems ≈ 1 GiB
_LEN = struct.Struct("<q")
CMD_PULL_SPARSE = 1
CMD_PUSH_SPARSE = 2
CMD_PULL_DENSE = 3
CMD_PUSH_DENSE = 4
CMD_STOP = 5
CMD_BARRIER = 6
CMD_PUSH_SHOW_CLICK = 7
CMD_DECAY = 8
CMD_SHRINK = 9
CMD_ADD_SPARSE = 10      # table-config negotiation (optimizer + accessor)
CMD_ADD_DENSE = 11
CMD_SAMPLE_NEIGHBORS = 12   # graph table: ids[n] -> [n, k] ids + weights
CMD_NODE_FEAT = 13          # graph table: ids[n] -> [n, feat_dim] f32
# Resilience extension (python plane). HELLO registers the client id for
# this connection — the id rides the header's NAME field (no payload), so
# a server that predates it (the native csrc/ps_server.cpp plane) answers
# with a plain unknown-cmd error frame and the stream stays in sync; the
# client then marks the endpoint legacy and keeps using unsequenced
# pushes. Sequenced pushes prefix their payload with an i64 request seq;
# the server applies each (client, seq) AT MOST ONCE, so a push retried
# after a lost ACK cannot double-apply the gradient.
CMD_HELLO = 14              # client id in the name field, no payload
CMD_PUSH_SPARSE_SEQ = 15    # i64 seq + CMD_PUSH_SPARSE payload
CMD_PUSH_DENSE_SEQ = 16     # i64 seq + CMD_PUSH_DENSE payload
# Durability/HA extension (PR 15). REPLICATE streams the WAL delta
# records after a watermark to a tailing standby (and doubles as the
# standby's ack: the watermark it sends IS its applied lsn). HA_STATUS
# returns a JSON role/watermark document; HANDBACK lets a recovered
# ex-primary hand the new primary any WAL records the replication tail
# missed (dedup'd by the seq ledger); FETCH_STATE is the full-state
# bootstrap a rejoining standby anchors its own WAL on.
CMD_PUSH_SHOW_CLICK_SEQ = 17  # i64 seq + CMD_PUSH_SHOW_CLICK payload
CMD_REPLICATE = 18            # i64 after_lsn + i64 max_records
CMD_HA_STATUS = 19            # no payload -> JSON frame
CMD_HANDBACK = 20             # i64 blob_len + concatenated records
CMD_FETCH_STATE = 21          # no payload -> meta JSON + npz blob
# delta-push plane (delta.py): a serving replica tails a sparse table's
# embedding ROWS (values, not optimizer slots) watermarked by commit lsn
CMD_DELTA = 22                # i64 after_lsn + i64 max_rows + subscriber id

from .table import OPT_WIRE_IDS as _OPT_IDS  # single source, both planes
_SPARSE_CFG = struct.Struct("<ffqBBfffffff")   # lr,std,seed,opt,ctr,b1,b2,eps,sdec,ccoef,dth,ttl
_DENSE_CFG = struct.Struct("<fqqBfff")          # lr,shard_lo,total,opt,b1,b2,eps
_ST_OK = b"\x01"
_ST_ERR = b"\x00"

_BARRIER_TIMEOUT = 60.0


class PsError(RuntimeError):
    """Server-reported request failure (carried in an error frame)."""


class CommunicatorFlushTimeout(TimeoutError):
    """`Communicator.flush` deadline expired with work still queued.
    The undelivered batches are NOT dropped: they stay parked with
    their original seqs and the next flush()/stop() delivers them."""

    def __init__(self, msg: str, pending: int = 0):
        super().__init__(msg)
        self.pending = pending


from ...utils import net as _net  # noqa: E402
from ...utils.net import recv_exact as _recv_exact  # noqa: E402
from ...utils import syncwatch as _syncwatch


def _tname(name: str) -> bytes:
    b = name.encode()
    if len(b) > 16:
        raise ValueError(
            f"ps table name {name!r} exceeds the 16-byte wire limit")
    return b.ljust(16, b"\0")


def _send_err(conn, msg: str):
    m = msg.encode()
    conn.sendall(_ST_ERR + _LEN.pack(len(m)) + m)


def _check_status(sock, deadline: Optional[float] = None):
    """Read the response status byte; raise PsError on an error frame.
    `deadline` (absolute monotonic) bounds the wait on a stalled peer."""
    st = _recv_exact(sock, 1, deadline)
    if st == _ST_OK:
        return
    (ln,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    raise PsError(_recv_exact(sock, ln, deadline).decode())


# live PsServer instances, for the conftest leak guard (`_no_ps_leak`)
_LIVE = weakref.WeakSet()


class PsServer:
    """One parameter-server process/thread (brpc_ps_server role).

    With `wal_dir` (or `FLAGS_ps_wal_dir`) set, every mutating request is
    committed to a CRC-framed write-ahead log BEFORE it touches a table,
    and `snapshot()` compacts the log into a crash-atomic generation (see
    `wal.py`). Construction over an existing wal_dir RECOVERS: newest
    intact snapshot + WAL replay, dedup'd by the persisted seq ledger, so
    a trainer retry replayed across the crash is still exactly-once.
    """

    def __init__(self, host="127.0.0.1", port=0, wal_dir: Optional[str] = None):
        self._tables: Dict[str, object] = {}
        # table name -> (kind, constructor cfg): rides the snapshot
        # manifest so recovery can rebuild tables before loading arrays
        self._cfgs: Dict[str, tuple] = {}
        self._sock = _net.make_listener(host, port, backlog=64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # open handler connections, so stop() can close them out from
        # under blocked recv_exact calls instead of leaking the threads
        self._conns: "weakref.WeakSet[socket.socket]" = weakref.WeakSet()
        # generation-counted barrier: CMD_BARRIER carries n participants;
        # the ACK is held until all n arrive (gloo-barrier role)
        self._barrier_cond = threading.Condition()
        self._barrier_arrived = 0
        self._barrier_gen = 0
        # at-most-once push ledger: (client id, request seq) applied set.
        # Floor+extras (wal.SeqLedger), NOT a monotonic high-water mark:
        # after a failover, a handed-back seq can arrive BELOW seqs the
        # new primary already applied and must still apply exactly once.
        self._ledger = _wal.SeqLedger()
        self._seq_lock = _syncwatch.lock("ps.PsServer._seq_lock")
        # ---- durability plane ----
        if wal_dir is None:
            wal_dir = str(_flags.flag("ps_wal_dir")) or None
        self.wal_dir = wal_dir
        self._wal: Optional[_wal.WalWriter] = None
        self._wal_lock = _syncwatch.lock("ps.PsServer._wal_lock")
        self._snap_lock = _syncwatch.lock("ps.PsServer._snap_lock")
        self._commits_since_snap = 0
        self._snap_every = int(_flags.flag("ps_snapshot_every_records"))
        self._snap_skip_warned = False
        # ---- HA plane (driven by ha.HaPsNode; inert otherwise) ----
        self.ha_role: Optional[str] = None
        self._repl_acks: Dict[str, int] = {}   # standby id -> acked lsn
        self._handback_floor = 0
        self.applied_lsn = 0
        # ---- delta-push plane (serving subscribers; see delta.py) ----
        # table -> key -> version of the commit that last touched the
        # row (value-shipping: the delta response reads the CURRENT row,
        # so a conservative extra mark is harmless, never wrong)
        self._delta_dirty: Dict[str, Dict[int, int]] = {}
        self._delta_acks: Dict[str, int] = {}  # subscriber id -> acked ver
        # subscribers at/below this watermark get a full-table resync:
        # mutations up to here predate the dirty map (recovery, install)
        self._delta_floor = 0
        self._delta_seq = 0   # version counter for WAL-less servers
        if wal_dir is not None:
            self._recover()
        self._closed = False
        _LIVE.add(self)

    # ---- durable state: recovery, commit, snapshot ----

    def _recover(self):
        """snapshot + WAL replay -> tables/ledger; then open the writer
        right after the last intact record (`wal.repair` truncates a torn
        tail so the next recovery can read past this session's appends)."""
        snap = _wal.load_snapshot(self.wal_dir)
        after = 0
        if snap is not None:
            for name, (kind, cfg) in snap.tables.items():
                self._install_table(name, kind, cfg)
            per_table: Dict[str, dict] = {}
            for key, arr in snap.arrays.items():
                tname, field = key.split("::", 1)
                per_table.setdefault(tname, {})[field] = arr
            for tname, arrs in per_table.items():
                self._tables[tname].load_arrays(arrs)
            self._ledger.load_state(snap.ledger)
            after = snap.lsn
        last = max(after, _wal.repair(self.wal_dir))
        for rec in _wal.replay(self.wal_dir, after_lsn=after):
            self._apply_record(rec)
            if _monitor._ENABLED:
                _monitor.count("ps.wal.records_replayed")
        self._wal = _wal.WalWriter(self.wal_dir, start_lsn=last + 1)
        self.applied_lsn = last
        # replayed mutations are not in the dirty map: any subscriber
        # whose watermark predates recovery needs a full resync
        self._delta_floor = last

    def _apply_record(self, rec: "_wal.Record"):
        """Apply one WAL record to the in-memory tables (recovery replay
        AND the standby's replication tail). Seq-stamped records go
        through the ledger: a delta that both reached the snapshot and
        survived in the log applies exactly once. A record whose apply
        raised live (e.g. decay on a dense table) raised BEFORE it was
        acked, so apply errors here are skipped, deterministically on
        every replica."""
        if rec.seq >= 0 and rec.client:
            with self._seq_lock:
                if not self._ledger.record(rec.client, rec.seq):
                    return False
        return self._apply_payload(rec)

    def _apply_payload(self, rec: "_wal.Record") -> bool:
        """Decode + apply one record's payload, WITHOUT the ledger check
        (callers own dedup). Exception-tolerant by contract — see
        `_apply_record`. True = applied."""
        try:
            if rec.rtype in (_wal.R_ADD_SPARSE, _wal.R_ADD_DENSE,
                             _wal.R_ADD_GRAPH):
                # idempotent on replay/handback: re-registering must NOT
                # clobber a live table with a fresh one
                if rec.table not in self._tables:
                    kind = {_wal.R_ADD_SPARSE: "sparse",
                            _wal.R_ADD_DENSE: "dense",
                            _wal.R_ADD_GRAPH: "graph"}[rec.rtype]
                    self._install_table(rec.table, kind,
                                        json.loads(rec.payload.decode()))
            elif rec.rtype == _wal.R_PUSH_SPARSE:
                ids, grads = _wal.unpack_push_sparse(rec.payload)
                self._tables[rec.table].push(ids, grads)
            elif rec.rtype == _wal.R_PUSH_DENSE:
                tbl = self._tables[rec.table]
                self._tables[rec.table].push(
                    _wal.unpack_push_dense(rec.payload).reshape(tbl.w.shape))
            elif rec.rtype == _wal.R_SHOW_CLICK:
                ids, shows, clicks = _wal.unpack_show_click(rec.payload)
                self._tables[rec.table].push_show_click(ids, shows, clicks)
            elif rec.rtype == _wal.R_DECAY:
                self._tables[rec.table].decay()
            elif rec.rtype == _wal.R_SHRINK:
                self._tables[rec.table].shrink()
        except (KeyError, ValueError, AttributeError, TypeError) as e:
            import warnings
            warnings.warn(f"ps wal replay: skipping lsn {rec.lsn} "
                          f"({type(e).__name__}: {e})")
            return False
        return True

    def _commit(self, rtype: int, name: str, client: Optional[str],
                seq: Optional[int], payload_fn: Callable[[], bytes],
                apply_fn: Callable[[], object], delta_ids=None):
        """The one mutating-request path: dedup -> WAL append -> apply,
        atomically w.r.t. snapshot collection (`_wal_lock`). Returns the
        apply result, or None for a deduplicated retry. Without a WAL the
        dedup + apply semantics are unchanged from PR 3.

        `delta_ids` names the sparse keys whose ROWS this commit may
        change (an id array, or a callable evaluated AFTER the apply —
        shrink only knows its evictions afterwards); they are stamped
        with the commit's version so delta subscribers pick them up."""
        if self._wal is None:
            if seq is not None and client:
                with self._seq_lock:
                    if not self._ledger.record(client, seq):
                        return None
            out = apply_fn()
            if delta_ids is not None:
                with self._wal_lock:
                    self._delta_seq += 1
                    self._mark_delta(name, delta_ids, self._delta_seq)
            return out
        with self._wal_lock:
            if seq is not None and client:
                with self._seq_lock:
                    if not self._ledger.record(client, seq):
                        return None
            lsn = self._wal.append(rtype, name, client or "",
                                   -1 if seq is None else seq, payload_fn())
            out = apply_fn()
            self.applied_lsn = lsn
            if delta_ids is not None:
                self._mark_delta(name, delta_ids, lsn)
            self._commits_since_snap += 1
        self._maybe_autosnapshot()
        return out

    def _mark_delta(self, name: str, ids, version: int) -> None:
        """Stamp keys dirty at `version` (caller holds `_wal_lock`)."""
        if callable(ids):
            ids = ids()
        if len(ids) == 0:
            return
        dirty = self._delta_dirty.setdefault(name, {})
        for k in ids:
            dirty[int(k)] = version

    def _delta_version(self) -> int:
        """Head of the delta stream: the WAL lsn when durable, a local
        commit counter otherwise (both monotonic per server lifetime)."""
        return self.applied_lsn if self._wal is not None else self._delta_seq

    def _maybe_autosnapshot(self):
        if not self._snap_every or self._commits_since_snap < self._snap_every:
            return
        try:
            self.snapshot()
        except _wal.PsSnapshotUnsupportedError:
            # a table type without a snapshot representation is
            # registered: auto-compaction cannot cover it, and a
            # serving-path push must never error for that
            if not self._snap_skip_warned:
                self._snap_skip_warned = True
                import warnings
                warnings.warn("ps: auto-snapshot skipped — a registered "
                              "table has no snapshot representation")
            self._commits_since_snap = 0
        except Exception:
            # a failed compaction (crashed mid-commit, disk error) must
            # not fail the push that tripped it: the WAL already holds
            # the commit, recovery falls back past an orphaned payload,
            # and the NEXT snapshot interval retries the compaction
            if _monitor._ENABLED:
                _monitor.count("ps.snapshot.failures")
            self._commits_since_snap = 0

    def collect_state(self):
        """Frozen (lsn, ledger, cfgs, arrays) under the commit lock —
        the payload for snapshot() and CMD_FETCH_STATE."""
        with self._wal_lock:
            for name, tbl in self._tables.items():
                if name not in self._cfgs:
                    raise _wal.PsSnapshotUnsupportedError(
                        f"ps: table {name!r} ({type(tbl).__name__}) has no "
                        "snapshot representation")
            with self._seq_lock:
                ledger = self._ledger.state()
            arrays = {}
            for name, tbl in self._tables.items():
                for field, arr in tbl.snapshot_arrays().items():
                    arrays[f"{name}::{field}"] = arr
            lsn = self.applied_lsn if self._wal is not None else 0
            self._commits_since_snap = 0
            return lsn, ledger, dict(self._cfgs), arrays

    def snapshot(self) -> int:
        """Compact the WAL into one crash-atomic generation; returns the
        new version. Graph tables ride along via `snapshot_arrays` (their
        content never hits the per-edge WAL — registration does, so a
        pre-snapshot crash recovers an empty-but-present graph). Raises
        PsSnapshotUnsupportedError when a registered table has no
        snapshot representation — never silent loss."""
        if self.wal_dir is None:
            raise ValueError("ps: snapshot() needs a wal_dir")
        with self._snap_lock:
            lsn, ledger, cfgs, arrays = self.collect_state()
            version = _wal.save_snapshot(self.wal_dir, lsn, ledger,
                                         cfgs, arrays)
            self._wal.sync()
            # drop segments every durable consumer is past: the FALLBACK
            # generation (previous manifest lsn) and every standby ack
            floor = min([lsn] + list(self._repl_acks.values()))
            prev = _wal._read_json(
                os.path.join(self.wal_dir, _wal._MANIFEST) + ".bak")
            if prev:
                floor = min(floor, int(prev.get("lsn", 0)))
            _wal.gc_segments(self.wal_dir, floor + 1)
            return version

    # ---- table registration ----

    def _install_table(self, name, kind, cfg):
        _tname(name)  # validate against the wire limit at registration
        if kind == "sparse":
            self._tables[name] = SparseTable(**cfg)
        elif kind == "dense":
            cfg = dict(cfg)
            shape = tuple(cfg.pop("shape"))
            self._tables[name] = DenseTable(shape, **cfg)
        elif kind == "graph":
            from .graph_table import GraphTable
            self._tables[name] = GraphTable(**cfg)
        else:
            raise ValueError(f"ps: unknown table kind {kind!r}")
        self._cfgs[name] = (kind, dict(cfg, shape=list(shape))
                            if kind == "dense" else cfg)
        return self._tables[name]

    def _log_add(self, rtype, name, cfg):
        if self._wal is not None:
            payload = json.dumps(cfg).encode()
            with self._wal_lock:
                self.applied_lsn = self._wal.append(rtype, name, "", -1,
                                                    payload)

    def add_sparse_table(self, name, dim, **kw):
        cfg = dict(kw, dim=dim)
        tbl = self._install_table(name, "sparse", cfg)
        self._log_add(_wal.R_ADD_SPARSE, name, cfg)
        return tbl

    def add_dense_table(self, name, shape, **kw):
        cfg = dict(kw, shape=list(np.atleast_1d(np.asarray(shape)).tolist())
                   if not np.isscalar(shape) else [int(shape)])
        tbl = self._install_table(name, "dense", cfg)
        self._log_add(_wal.R_ADD_DENSE, name, self._cfgs[name][1])
        return tbl

    def add_graph_table(self, name, **kw):
        # graph edges/features stay OUTSIDE the WAL record stream
        # (load-once read-only state, no per-edge commits) but ride the
        # snapshot/fetch-state plane via GraphTable.snapshot_arrays, so
        # recovery and standby bootstrap carry the feature source too
        tbl = self._install_table(name, "graph", kw)
        self._log_add(_wal.R_ADD_GRAPH, name, kw)
        return tbl

    def table(self, name):
        return self._tables[name]

    def run(self, block=False):
        self._thread = _syncwatch.Thread(target=self._serve, daemon=True,
                                        name="ps-serve")
        self._thread.start()
        if block:
            self._thread.join()
        return self

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                # one flag flip secures the PS plane: TLS + 'PDAH' auth,
                # unauthenticated peers rejected + counted
                conn = _net.secure_server(conn, "ps")
            except (_net.AuthError, OSError, ValueError):
                continue
            _syncwatch.Thread(target=self._handle, args=(conn,),
                             daemon=True, name="ps-handler").start()

    def _barrier(self, n_participants: int):
        with self._barrier_cond:
            gen = self._barrier_gen
            self._barrier_arrived += 1
            if self._barrier_arrived >= max(n_participants, 1):
                self._barrier_arrived = 0
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
                return
            if not self._barrier_cond.wait_for(
                    lambda: self._barrier_gen != gen,
                    timeout=_BARRIER_TIMEOUT):
                # roll back our arrival so later generations aren't corrupted
                if self._barrier_gen == gen:
                    self._barrier_arrived -= 1
                raise PsError(
                    f"barrier timed out after {_BARRIER_TIMEOUT}s "
                    f"({n_participants} participants expected)")

    def _handle(self, conn):
        client_id: Optional[str] = None   # set by CMD_HELLO, per connection
        self._conns.add(conn)
        try:
            while True:
                # recv_head strips an optional 'PDDL' deadline prefix and
                # DROPS already-expired work (DeadlineExpiredError lands
                # in the outer except: connection closed, nothing computed)
                hdr, _req_deadline = _net.recv_head(conn, _HDR.size,
                                                    plane="ps")
                cmd, name, n, dim = _HDR.unpack(hdr)
                name = name.rstrip(b"\0").decode()
                if _faults._ENABLED:
                    # injected conn_reset lands in the outer except and
                    # drops this handler's connection — the server stays
                    # up, the client reconnects and retries
                    _faults.check("ps.server")
                # bound the (client-controlled) payload size before any
                # allocation: a corrupt/hostile header must produce an
                # error frame + connection drop, not a multi-GB buffer or
                # a dead handler thread
                if not (0 <= n <= _MAX_PAYLOAD_ROWS
                        and 0 <= dim <= _MAX_PAYLOAD_DIM
                        and n * max(dim, 1) <= _MAX_PAYLOAD_ELEMS):
                    _send_err(conn, f"ps: implausible header n={n} "
                                    f"dim={dim}")
                    return
                # read the FULL request payload before processing so an
                # error reply leaves the stream in sync for the next request
                ids = grads = None
                req_seq = None
                repl_args = blob = None
                if cmd == CMD_PUSH_SPARSE_SEQ:
                    (req_seq,) = _LEN.unpack(_recv_exact(conn, 8))
                    cmd = CMD_PUSH_SPARSE
                elif cmd == CMD_PUSH_DENSE_SEQ:
                    (req_seq,) = _LEN.unpack(_recv_exact(conn, 8))
                    cmd = CMD_PUSH_DENSE
                elif cmd == CMD_PUSH_SHOW_CLICK_SEQ:
                    (req_seq,) = _LEN.unpack(_recv_exact(conn, 8))
                    cmd = CMD_PUSH_SHOW_CLICK
                if cmd == CMD_REPLICATE:
                    repl_args = _LEN.unpack(_recv_exact(conn, 8)) \
                        + _LEN.unpack(_recv_exact(conn, 8))
                elif cmd == CMD_DELTA:
                    after_v = _LEN.unpack(_recv_exact(conn, 8))[0]
                    max_rows = _LEN.unpack(_recv_exact(conn, 8))[0]
                    (slen,) = _LEN.unpack(_recv_exact(conn, 8))
                    if not 0 <= slen <= 256:
                        _send_err(conn, f"ps: implausible subscriber id "
                                        f"length {slen}")
                        return
                    repl_args = (after_v, max_rows,
                                 _recv_exact(conn, slen).decode())
                elif cmd == CMD_HANDBACK:
                    (blen,) = _LEN.unpack(_recv_exact(conn, 8))
                    if not 0 <= blen <= 4 * _MAX_PAYLOAD_ELEMS:
                        _send_err(conn, f"ps: implausible handback {blen}")
                        return
                    blob = _recv_exact(conn, blen)
                if cmd == CMD_PULL_SPARSE:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                elif cmd == CMD_PUSH_SPARSE:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    grads = np.frombuffer(
                        _recv_exact(conn, 4 * n * dim), np.float32
                    ).reshape(n, dim)
                elif cmd == CMD_PUSH_DENSE:
                    grads = np.frombuffer(_recv_exact(conn, 4 * n), np.float32)
                elif cmd == CMD_PUSH_SHOW_CLICK:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    grads = np.frombuffer(
                        _recv_exact(conn, 4 * n * 2), np.float32)
                elif cmd == CMD_ADD_SPARSE:
                    cfg_raw = _recv_exact(conn, _SPARSE_CFG.size)
                elif cmd == CMD_ADD_DENSE:
                    cfg_raw = _recv_exact(conn, _DENSE_CFG.size)
                elif cmd in (CMD_SAMPLE_NEIGHBORS, CMD_NODE_FEAT):
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                try:
                    if cmd == CMD_STOP:
                        conn.sendall(_ST_OK)
                        self._stop.set()
                        return
                    if cmd == CMD_BARRIER:
                        self._barrier(int(n))
                        conn.sendall(_ST_OK)
                        continue
                    if cmd == CMD_HELLO:
                        client_id = name
                        conn.sendall(_ST_OK)
                        continue
                    if req_seq is not None and client_id is None:
                        raise PsError("ps: sequenced push before CMD_HELLO")
                    if cmd == CMD_REPLICATE:
                        self._serve_replicate(conn, name, *repl_args)
                        continue
                    if cmd == CMD_DELTA:
                        from . import delta as _delta
                        _delta.serve_delta(self, conn, name, *repl_args)
                        continue
                    if cmd == CMD_HA_STATUS:
                        doc = json.dumps(self.ha_status()).encode()
                        conn.sendall(_ST_OK + _LEN.pack(len(doc)) + doc)
                        continue
                    if cmd == CMD_HANDBACK:
                        applied = self._serve_handback(blob)
                        conn.sendall(_ST_OK + _LEN.pack(applied))
                        continue
                    if cmd == CMD_FETCH_STATE:
                        self._serve_fetch_state(conn)
                        continue
                    if cmd == CMD_ADD_SPARSE:
                        (lr, istd, seed, opt, ctr, b1, b2, eps, sdec, ccoef,
                         dth, ttl) = _SPARSE_CFG.unpack(cfg_raw)
                        if name in self._tables:
                            raise ValueError(
                                f"ps: table {name!r} already registered")
                        opt_name = {0: "sgd", 1: "adagrad", 2: "adam"}[opt]
                        kw = {}
                        if ctr:
                            kw = dict(accessor="ctr", show_decay_rate=sdec,
                                      click_coeff=ccoef,
                                      delete_threshold=dth, ttl_days=ttl)
                        self.add_sparse_table(
                            name, int(dim), optimizer=opt_name, lr=lr,
                            init_std=istd, seed=int(seed), beta1=b1,
                            beta2=b2, eps=eps, **kw)
                        conn.sendall(_ST_OK)
                        continue
                    if cmd == CMD_ADD_DENSE:
                        lr, lo, total, opt, b1, b2, eps = \
                            _DENSE_CFG.unpack(cfg_raw)
                        if name in self._tables:
                            raise ValueError(
                                f"ps: table {name!r} already registered")
                        opt_name = {0: "sgd", 1: "adagrad", 2: "adam"}[opt]
                        self.add_dense_table(name, (int(n),),
                                             optimizer=opt_name, lr=lr,
                                             beta1=b1, beta2=b2, eps=eps,
                                             shard_lo=int(lo),
                                             total_size=int(total) if
                                             total > 0 else int(n))
                        conn.sendall(_ST_OK)
                        continue
                    tbl = self._tables.get(name)
                    if tbl is None:
                        raise KeyError(f"ps: unknown table {name!r}")
                    if cmd == CMD_PULL_SPARSE:
                        rows = tbl.pull(ids)
                        conn.sendall(_ST_OK + rows.astype(np.float32).tobytes())
                    elif cmd == CMD_PUSH_SPARSE:
                        self._commit(
                            _wal.R_PUSH_SPARSE, name, client_id, req_seq,
                            lambda: _wal.pack_push_sparse(ids, grads),
                            lambda: tbl.push(ids, grads), delta_ids=ids)
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_PULL_DENSE:
                        w = tbl.pull().astype(np.float32)
                        lo, _hi = getattr(tbl, "shard_range", (0, w.size))
                        total = getattr(tbl, "total_size", w.size)
                        # slice + (offset, total) so the client can verify
                        # the shards tile exactly one table
                        conn.sendall(_ST_OK + _LEN.pack(w.size)
                                     + _LEN.pack(lo) + _LEN.pack(total)
                                     + w.tobytes())
                    elif cmd == CMD_PUSH_DENSE:
                        self._commit(
                            _wal.R_PUSH_DENSE, name, client_id, req_seq,
                            lambda: _wal.pack_push_dense(grads),
                            lambda: tbl.push(grads.reshape(tbl.w.shape)))
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_PUSH_SHOW_CLICK:
                        self._commit(
                            _wal.R_SHOW_CLICK, name, client_id, req_seq,
                            lambda: _wal.pack_show_click(
                                ids, grads[:n], grads[n:]),
                            lambda: tbl.push_show_click(
                                ids, grads[:n], grads[n:]))
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_DECAY:
                        # decay/shrink carry no client seq: durable but
                        # at-least-once across a handback (documented)
                        self._commit(_wal.R_DECAY, name, None, None,
                                     lambda: b"", tbl.decay)
                        conn.sendall(_ST_OK)
                    elif cmd == CMD_SHRINK:
                        # tombstones are only known after the apply, so
                        # the mark is a callable over the table's record
                        evicted = self._commit(
                            _wal.R_SHRINK, name, None, None, lambda: b"",
                            tbl.shrink,
                            delta_ids=lambda: getattr(
                                tbl, "last_shrink_evicted", ()))
                        conn.sendall(_ST_OK + _LEN.pack(int(evicted)))
                    elif cmd == CMD_SAMPLE_NEIGHBORS:
                        nb, w = tbl.sample_neighbors(ids, int(dim))
                        conn.sendall(_ST_OK + nb.astype(np.int64).tobytes()
                                     + w.astype(np.float32).tobytes())
                    elif cmd == CMD_NODE_FEAT:
                        f = tbl.get_node_feat(ids).astype(np.float32)
                        conn.sendall(_ST_OK + _LEN.pack(f.shape[1])
                                     + f.tobytes())
                    else:
                        raise ValueError(f"ps: unknown command {cmd}")
                except (KeyError, ValueError, PsError, AttributeError,
                        TypeError) as e:
                    # AttributeError/TypeError: a table-op aimed at a table
                    # type without that surface (e.g. DECAY on a dense
                    # table) must produce a protocol error frame — the C++
                    # server answers the same request with one
                    _send_err(conn, str(e))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # ---- HA verbs (server side; driven by ha.HaPsNode + PsClient) ----

    def _serve_replicate(self, conn, standby_id: str, after_lsn: int,
                         max_records: int):
        """Stream WAL records with lsn > after_lsn to a tailing standby.
        The request watermark doubles as the standby's ack — segment GC
        and the bounded-staleness guarantee key off it. Reads happen
        under the commit lock so a record mid-append is never torn."""
        if self._wal is None:
            raise PsError("ps: replication needs a wal_dir")
        if standby_id:
            self._repl_acks[standby_id] = int(after_lsn)
        with self._wal_lock:
            recs = _wal.replay(self.wal_dir, after_lsn=int(after_lsn),
                               max_records=int(max_records) or None,
                               count_fallback=False)
            frames = [_wal.encode_record(r) for r in recs]
        blen = sum(len(f) for f in frames)
        # scatter-gather: the already-encoded records go to the kernel
        # as-is instead of being re-joined into one blob copy
        _net.send_frames(conn, [_ST_OK + _LEN.pack(len(recs))
                                + _LEN.pack(blen)] + frames)

    def ha_status(self) -> dict:
        return {"role": self.ha_role, "applied_lsn": self.applied_lsn,
                "handback_floor": self._handback_floor,
                "acks": dict(self._repl_acks),
                "wal": self.wal_dir is not None}

    def _serve_handback(self, blob: bytes) -> int:
        """A recovered ex-primary hands over WAL records the replication
        tail never saw (lsn > our handback floor). Each is committed as a
        FRESH record in our own stream; the seq ledger drops anything the
        client base already re-pushed after failover — exactly-once
        either way the race lands."""
        applied = 0
        for rec in _wal.decode_stream(blob):
            if rec.lsn <= self._handback_floor:
                continue
            if (rec.rtype in (_wal.R_ADD_SPARSE, _wal.R_ADD_DENSE,
                              _wal.R_ADD_GRAPH)
                    and rec.table in self._tables):
                continue   # already registered: no duplicate WAL record
            out = self._commit(rec.rtype, rec.table, rec.client or None,
                               rec.seq if rec.seq >= 0 else None,
                               lambda: rec.payload,
                               lambda: self._apply_payload(rec),
                               delta_ids=lambda rec=rec:
                                   self._delta_ids_for(rec))
            if out:
                applied += 1
        if applied and _monitor._ENABLED:
            _monitor.count("ps.handback.records", applied)
        return applied

    def _serve_fetch_state(self, conn):
        """Full-state bootstrap for a rejoining standby: frozen meta
        (lsn + ledger + table configs) and an npz blob of every array."""
        import io
        lsn, ledger, cfgs, arrays = self.collect_state()
        meta = json.dumps({"lsn": lsn, "ledger": ledger,
                           "tables": cfgs}).encode()
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        blob = buf.getbuffer()
        _net.send_frames(conn, [_ST_OK + _LEN.pack(len(meta)) + meta
                                + _LEN.pack(blob.nbytes), blob])

    def delta_since(self, name: str, after_version: int, max_rows: int = 0,
                    subscriber: str = ""):
        """The delta-push plane's read side (CMD_DELTA; see delta.py).

        Returns `(version, dim, full, live_keys, rows, dead_keys)` —
        every sparse ROW touched by a commit after `after_version`
        (values only, never optimizer slots), plus tombstones for
        evicted keys. `full=True` (watermark below the resync floor —
        a fresh subscriber, or this server recovered/installed state)
        means the payload is the WHOLE table and the subscriber must
        replace, not merge. `max_rows` cuts the incremental path on a
        version boundary only — the returned watermark is always safe
        to resume from. The request watermark doubles as the
        subscriber's ack: tombstones every subscriber has passed are
        dropped. Runs under the commit lock, so a row mid-push is
        never shipped torn."""
        tbl = self._tables.get(name)
        if not isinstance(tbl, SparseTable):
            raise PsError(f"ps: delta stream needs a sparse table, "
                          f"{name!r} is {type(tbl).__name__}")
        after = int(after_version)
        with self._wal_lock:
            if subscriber:
                self._delta_acks[subscriber] = after
            version = self._delta_version()
            if after < self._delta_floor:
                with tbl._lock:
                    live = list(tbl._rows.keys())
                    block = self._stack_rows(
                        [tbl._rows[k] for k in live], tbl.dim)
                return version, tbl.dim, True, live, block, []
            dirty = self._delta_dirty.get(name, {})
            items = sorted((ver, k) for k, ver in dirty.items()
                           if ver > after)
            if max_rows and len(items) > max_rows:
                cut = int(max_rows)
                edge = items[cut - 1][0]
                while cut < len(items) and items[cut][0] == edge:
                    cut += 1   # never split one commit across pulls
                items = items[:cut]
                version = items[-1][0]
            live, dead, rows = [], [], []
            with tbl._lock:
                for _ver, k in items:
                    r = tbl._rows.get(k)
                    if r is None:
                        dead.append(k)
                    else:
                        live.append(k)
                        rows.append(r)
                block = self._stack_rows(rows, tbl.dim)
            if self._delta_acks:
                floor = min(self._delta_acks.values())
                stale = [k for k, ver in dirty.items()
                         if ver <= floor and k not in tbl._rows]
                for k in stale:
                    del dirty[k]
            return version, tbl.dim, False, live, block, dead

    @staticmethod
    def _stack_rows(rows, dim) -> np.ndarray:
        if not rows:
            return np.zeros((0, dim), np.float32)
        return np.stack(rows).astype(np.float32, copy=False)

    def _delta_ids_for(self, rec: "_wal.Record"):
        """Sparse keys whose rows a replicated/handed-back record may
        have changed (evaluated AFTER the record applied). Stats-only
        records (show/click, decay) leave embedding rows untouched."""
        if rec.rtype == _wal.R_PUSH_SPARSE:
            ids, _ = _wal.unpack_push_sparse(rec.payload)
            return ids
        if rec.rtype == _wal.R_SHRINK:
            tbl = self._tables.get(rec.table)
            return getattr(tbl, "last_shrink_evicted", ()) if tbl else ()
        return ()

    def apply_replicated(self, rec: "_wal.Record"):
        """Standby-side: persist one replicated record under its ORIGINAL
        lsn (both WALs carry the identical stream), then apply through
        the same ledger/dedup discipline as the primary."""
        with self._wal_lock:
            if self._wal is not None:
                self._wal.append_record(rec)
            self._apply_record(rec)
            self.applied_lsn = rec.lsn
            self._mark_delta(rec.table, self._delta_ids_for(rec), rec.lsn)
            self._commits_since_snap += 1
        if _monitor._ENABLED:
            _monitor.count("ps.replication.records")
        self._maybe_autosnapshot()

    def reset_state(self):
        """Drop every table, the ledger, and the local WAL directory —
        the rejoin flow calls this after handback, right before anchoring
        on the new primary's `install_state` payload."""
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
            self._tables.clear()
            self._cfgs.clear()
            with self._seq_lock:
                self._ledger = _wal.SeqLedger()
            self.applied_lsn = 0
            self._delta_dirty.clear()
            self._delta_floor = 0
            self._delta_seq = 0
            if self.wal_dir is not None:
                _wal.wipe(self.wal_dir)

    def install_state(self, meta: dict, blob: bytes):
        """Install a `_serve_fetch_state` payload and anchor the local
        durability chain on it: the state becomes snapshot generation 1
        at the primary's lsn, and the WAL writer opens at lsn + 1."""
        import io
        npz = np.load(io.BytesIO(blob))
        arrays = {k: npz[k] for k in npz.files}
        with self._wal_lock:
            for name, kc in meta["tables"].items():
                self._install_table(name, kc[0], kc[1])
            per_table: Dict[str, dict] = {}
            for key, arr in arrays.items():
                tname, field = key.split("::", 1)
                per_table.setdefault(tname, {})[field] = arr
            for tname, arrs in per_table.items():
                self._tables[tname].load_arrays(arrs)
            with self._seq_lock:
                self._ledger.load_state(meta["ledger"])
            lsn = int(meta["lsn"])
            if self.wal_dir is not None:
                _wal.save_snapshot(self.wal_dir, lsn, meta["ledger"],
                                   {n: (kc[0], kc[1]) for n, kc in
                                    meta["tables"].items()}, arrays)
                self._wal = _wal.WalWriter(self.wal_dir, start_lsn=lsn + 1)
            self.applied_lsn = lsn
            # installed arrays are not in the dirty map: delta
            # subscribers at or below this point need a full resync
            self._delta_floor = lsn
            self._delta_seq = lsn

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # unblock handler threads parked in recv_exact — their sockets
        # are owned here so tests can assert nothing leaks
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()
        self._closed = True


_CLIENT_SEQ = [0]
_CLIENT_SEQ_LOCK = threading.Lock()


def _new_client_id() -> bytes:
    """16-byte wire client id, unique across processes and instances
    (pid + in-process counter, hex — fits the header's name field)."""
    with _CLIENT_SEQ_LOCK:
        _CLIENT_SEQ[0] += 1
        n = _CLIENT_SEQ[0]
    return f"{os.getpid() % 0xFFFF:04x}{n % 0xFFFF:04x}" \
        f"{random.getrandbits(32):08x}".encode()


class PsClient:
    """Sharded client (brpc_ps_client role): sparse ids route to server
    `id % n_servers`; dense tables are row-range sharded across all
    servers (pull concatenates, push scatters).

    Self-healing transport: a transport error invalidates the cached
    connection, and every data-plane RPC is retried with exponential
    backoff + jitter up to `max_retries` times, reconnecting
    transparently (`ps.retries` / `ps.reconnects` monitor counters).
    Pulls are idempotent and retried freely; pushes carry a per-client
    request sequence (CMD_HELLO capability handshake per connection) so a
    push retried after a lost ACK is applied AT MOST ONCE server-side.
    Endpoints that reject CMD_HELLO (the native C++ plane) are marked
    legacy and keep plain at-least-once pushes. `call_timeout` bounds
    connect and each response read, so a stalled-but-open server raises
    TimeoutError (feeding the retry loop) instead of hanging the caller.
    """

    def __init__(self, endpoints: Optional[Sequence[str]] = None,
                 max_retries: Optional[int] = None,
                 backoff_ms: Optional[float] = None,
                 call_timeout: Optional[float] = None,
                 resolver: Optional[Callable[[], Sequence[str]]] = None):
        # `resolver` re-reads the current endpoint set (HA: the
        # rendezvous store's primary records) — consulted once up front
        # when `endpoints` is omitted, and again inside the retry loop
        # after every transport failure, so a failed-over primary is
        # picked up WITHIN the original per-call deadline
        self._resolver = resolver
        if endpoints is None:
            if resolver is None:
                raise ValueError("PsClient needs endpoints or a resolver")
            endpoints = resolver()
        self.endpoints = list(endpoints)
        self.max_retries = int(_flags.flag("ps_rpc_max_retries")
                               if max_retries is None else max_retries)
        self.backoff_s = float(_flags.flag("ps_rpc_backoff_ms")
                               if backoff_ms is None else backoff_ms) / 1e3
        ct = float(_flags.flag("ps_rpc_call_timeout_s")
                   if call_timeout is None else call_timeout)
        self.call_timeout = ct if ct > 0 else None
        # one RpcChannel per shard: the substrate owns connect/reconnect,
        # the security stack, and the plane's fault sites; this client
        # keeps only the sharding + verb framing
        self._chans: List[_net.RpcChannel] = [
            self._make_chan(ep) for ep in endpoints]
        # one shared syncwatch name: shard locks are acquired in ascending
        # shard order by protocol, so order edges between them are noise
        self._locks = [_syncwatch.lock("ps.PsClient._locks[]")
                       for _ in endpoints]
        self._dims: Dict[str, int] = {}  # table -> row dim (accessor config)
        self._dense_sizes: Dict[str, list] = {}  # table -> per-server sizes
        self._client_id = _new_client_id()
        self._push_seq = [0] * len(endpoints)   # per-server request seq
        # per-CONNECTION hello state (None = not negotiated yet) and the
        # per-ENDPOINT legacy verdict (sticky: a native server stays one)
        self._hello_ok: List[Optional[bool]] = [None] * len(endpoints)
        self._legacy = [False] * len(endpoints)

    def _make_chan(self, ep: str) -> "_net.RpcChannel":
        return _net.RpcChannel(
            "ps", endpoint=ep, connect_timeout=self.call_timeout or 120,
            legacy_sites=("ps.rpc.send", "ps.rpc.recv"),
            legacy_reconnect_counter="ps.reconnects")

    def _sock(self, i):
        return self._chans[i].sock

    def _drop(self, i):
        # a transport error leaves the stream byte-desynced: close and
        # forget the connection so the next request starts clean
        self._chans[i].drop()
        self._hello_ok[i] = None   # renegotiate on the next connection

    def _deadline(self) -> Optional[float]:
        return (time.monotonic() + self.call_timeout
                if self.call_timeout else None)

    def _refresh_endpoints(self) -> bool:
        """Re-resolve the endpoint set after a transport failure. The
        shard count must be stable (ids route by `id % n_servers`);
        per-server push seqs are KEPT — the standby replicated the
        primary's ledger, so in-flight retries stay exactly-once."""
        if self._resolver is None:
            return False
        try:
            new = list(self._resolver())
        except Exception:
            return False
        if not new or new == self.endpoints or len(new) != len(self.endpoints):
            return False
        for i in range(len(self._chans)):
            self._drop(i)
            self._chans[i].endpoint = new[i]
        self.endpoints = new
        self._legacy = [False] * len(new)
        if _monitor._ENABLED:
            _monitor.count("ps.failovers")
        return True

    def _retry_rpc(self, attempt_fn, op: str = "call"):
        """Run one RPC attempt; on a transport failure (OSError family —
        includes injected resets and recv deadlines) back off and retry.
        Server-reported PsErrors are application failures: never retried.
        Caller must already hold the involved per-server locks so a
        retried push reuses its sequence numbers without interleaving.

        Under `FLAGS_trace` the WHOLE call (retries included) is one
        `ps.rpc.<op>` span — parented on the calling thread's open span
        when there is one — that closes with error status when the RPC
        ultimately fails (injected `ps.rpc.send` conn-resets/timeouts
        land here: no leaked open spans)."""
        # with a resolver the retry budget is the CALL DEADLINE, not a
        # fixed count: failover (lease expiry + standby promotion) can
        # take several backoff rounds, and the contract is reaching the
        # new primary within the original per-call deadline
        return _net.call_with_retry(
            attempt_fn, plane="ps", op=op,
            max_retries=self.max_retries, backoff_s=self.backoff_s,
            deadline=(self._deadline() if self._resolver is not None
                      else None),
            retry_on=(OSError,), no_retry=(PsError,),
            on_transport_error=self._refresh_endpoints,
            span_name=f"ps.rpc.{op}", legacy_retry_counter="ps.retries")

    def _ensure_seq(self, s: int) -> bool:
        """True when the CURRENT connection to server s has a registered
        client id (sequenced pushes allowed). One HELLO per connection;
        an error frame marks the endpoint legacy for good."""
        if self._legacy[s]:
            return False
        sk = self._sock(s)
        if self._hello_ok[s] is not None:
            return self._hello_ok[s]
        try:
            sk.sendall(_HDR.pack(CMD_HELLO, self._client_id, 0, 0))
            _check_status(sk, self._deadline())
            self._hello_ok[s] = True
        except PsError:
            self._legacy[s] = True
            self._hello_ok[s] = False
        except OSError:
            self._drop(s)
            raise
        return self._hello_ok[s]

    def _next_push_seq(self, s: int) -> int:
        self._push_seq[s] += 1
        return self._push_seq[s]

    def _shard_sel(self, ids):
        n_srv = len(self.endpoints)
        m = ids % n_srv  # one modulo pass over the id vector
        out = []
        for s in range(n_srv):
            sel = np.where(m == s)[0]
            if len(sel):
                out.append((s, sel))
        return out

    def _send_all(self, shards, make_payload):
        """Send one request per shard; on a transport error every involved
        socket is dropped (earlier sends may have unread responses that
        would byte-desync a reused connection)."""
        wire_dl = (self._deadline()
                   if _net.deadline_wire_enabled() else None)
        try:
            for s, sel in shards:
                self._chans[s].sendall(make_payload(s, sel), wire_dl)
        except OSError:
            for s, _ in shards:
                self._drop(s)
            raise

    def _recv_all(self, shards, recv_one, deadline: Optional[float] = None):
        """Read every shard's response even if one errors (keeps the other
        sockets in sync); re-raise the first failure afterwards."""
        first: Optional[BaseException] = None
        for s, sel in shards:
            ch = self._chans[s]
            if not ch.connected:
                continue
            try:
                ch.check_recv_faults()
                sk = ch.sock
                _check_status(sk, deadline)
                if recv_one is not None:
                    recv_one(s, sel, sk)
            except OSError as e:
                self._drop(s)
                first = first or e
            except PsError as e:
                first = first or e
        if first is not None:
            raise first

    # -- sparse --
    def register_sparse_dim(self, table: str, dim: int):
        """Client-side table metadata (the reference ships this in the
        TableAccessor config)."""
        self._dims[table] = dim

    def pull_sparse(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        dim = self._dims[table]
        shards = self._shard_sel(ids)
        out = np.empty((len(ids), dim), np.float32)
        # acquire in ascending shard order (deadlock-free), send all
        # requests, then collect all responses: ~one RTT total
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: (
                    _HDR.pack(CMD_PULL_SPARSE, _tname(table), len(sel), 0)
                    + ids[sel].tobytes()))

                def recv_rows(s, sel, sk):
                    out[sel] = np.frombuffer(
                        _recv_exact(sk, 4 * len(sel) * dim, deadline),
                        np.float32).reshape(len(sel), dim)

                self._recv_all(shards, recv_rows, deadline)

            self._retry_rpc(attempt, op="pull_sparse")
        finally:
            for s, _ in shards:
                self._locks[s].release()
        return out

    def _call_seqs(self, shards, _seqs):
        """One seq per involved server for the WHOLE call: every retry
        resends the same seq, so the server applies it at most once. The
        optional `_seqs` box lets a caller that re-issues the call later
        (Communicator requeue after failover) REUSE the original seqs —
        the ledger then drops whatever the dead primary already shipped."""
        seqs = _seqs if _seqs is not None else {}
        for s, _ in shards:
            if s not in seqs:
                seqs[s] = self._next_push_seq(s)
        return seqs

    def push_sparse(self, table: str, ids, grads, _seqs=None):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        shards = self._shard_sel(ids)
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            seqs = self._call_seqs(shards, _seqs)

            def attempt():
                deadline = self._deadline()

                def payload(s, sel):
                    g = grads[sel]  # one fancy-index copy per shard
                    if self._ensure_seq(s):
                        return (_HDR.pack(CMD_PUSH_SPARSE_SEQ, _tname(table),
                                          len(sel), g.shape[1])
                                + _LEN.pack(seqs[s])
                                + ids[sel].tobytes() + g.tobytes())
                    return (_HDR.pack(CMD_PUSH_SPARSE, _tname(table),
                                      len(sel), g.shape[1])
                            + ids[sel].tobytes() + g.tobytes())

                self._send_all(shards, payload)
                self._recv_all(shards, None, deadline)

            self._retry_rpc(attempt, op="push_sparse")
        finally:
            for s, _ in shards:
                self._locks[s].release()

    # -- dense --
    # Dense tables are row-range sharded across ALL servers (reference
    # `common_dense_table.cc`): pull fans one request per server and
    # concatenates the slices; push scatters the grad by the same ranges.
    # Slice sizes are learned on the first pull (each response carries its
    # size) and cached for pushes.

    def pull_dense(self, table: str) -> np.ndarray:
        n_srv = len(self.endpoints)
        shards = [(s, None) for s in range(n_srv)]
        parts: list = [None] * n_srv
        metas: list = [None] * n_srv
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: _HDR.pack(
                    CMD_PULL_DENSE, _tname(table), 0, 0))

                def recv_slice(s, sel, sk):
                    (size,) = _LEN.unpack(_recv_exact(sk, 8, deadline))
                    (lo,) = _LEN.unpack(_recv_exact(sk, 8, deadline))
                    (total,) = _LEN.unpack(_recv_exact(sk, 8, deadline))
                    metas[s] = (lo, size, total)
                    parts[s] = np.frombuffer(
                        _recv_exact(sk, 4 * size, deadline),
                        np.float32).copy()

                self._recv_all(shards, recv_slice, deadline)

            self._retry_rpc(attempt, op="pull_dense")
        finally:
            for s, _ in shards:
                self._locks[s].release()
        # the per-server slices must tile [0, total) exactly — this catches
        # tables registered unsharded on several servers (duplicate full
        # copies) or with inconsistent shard specs
        total = metas[0][2]
        ordered = sorted(range(n_srv), key=lambda s: metas[s][0])
        cursor = 0
        for s in ordered:
            lo, size, tot = metas[s]
            if tot != total or lo != cursor:
                raise PsError(
                    f"pull_dense('{table}'): server shards do not tile the "
                    f"table (server {s} reports offset {lo} size {size} "
                    f"total {tot}; expected offset {cursor} total {total}) "
                    "— register with shard=(i, n_servers) on every server")
            cursor += size
        if cursor != total:
            raise PsError(
                f"pull_dense('{table}'): shards cover {cursor} of {total} "
                "elements")
        self._dense_sizes[table] = [(metas[s][0], metas[s][1])
                                    for s in range(n_srv)]
        return np.concatenate([parts[s] for s in ordered])

    def push_dense(self, table: str, grad, _seqs=None):
        g = np.asarray(grad, np.float32).reshape(-1)
        ranges = self._dense_sizes.get(table)
        if ranges is None:
            self.pull_dense(table)  # learn (and validate) the shard split
            ranges = self._dense_sizes[table]
        total = sum(size for _, size in ranges)
        if total != g.size:
            raise PsError(
                f"push_dense('{table}'): grad size {g.size} != table size "
                f"{total}")
        shards = [(s, (lo, lo + size))
                  for s, (lo, size) in enumerate(ranges) if size]
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            seqs = self._call_seqs(shards, _seqs)

            def attempt():
                deadline = self._deadline()

                def payload(s, sel):
                    body = g[sel[0]:sel[1]].tobytes()
                    if self._ensure_seq(s):
                        return (_HDR.pack(CMD_PUSH_DENSE_SEQ, _tname(table),
                                          sel[1] - sel[0], 0)
                                + _LEN.pack(seqs[s]) + body)
                    return (_HDR.pack(CMD_PUSH_DENSE, _tname(table),
                                      sel[1] - sel[0], 0) + body)

                self._send_all(shards, payload)
                self._recv_all(shards, None, deadline)

            self._retry_rpc(attempt, op="push_dense")
        finally:
            for s, _ in shards:
                self._locks[s].release()

    # -- CTR accessor ops (ctr_accessor.cc role over the wire) --
    def push_show_click(self, table: str, ids, shows, clicks, _seqs=None):
        """Bump per-row show/click statistics on the owning servers.
        Sequenced like the gradient pushes (CMD_PUSH_SHOW_CLICK_SEQ): a
        counter bump retried across a failover lands exactly once."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        shows = np.asarray(shows, np.float32).reshape(-1)
        clicks = np.asarray(clicks, np.float32).reshape(-1)
        shards = self._shard_sel(ids)
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            seqs = self._call_seqs(shards, _seqs)

            def attempt():
                deadline = self._deadline()

                def payload(s, sel):
                    body = (ids[sel].tobytes() + shows[sel].tobytes()
                            + clicks[sel].tobytes())
                    if self._ensure_seq(s):
                        return (_HDR.pack(CMD_PUSH_SHOW_CLICK_SEQ,
                                          _tname(table), len(sel), 0)
                                + _LEN.pack(seqs[s]) + body)
                    return (_HDR.pack(CMD_PUSH_SHOW_CLICK, _tname(table),
                                      len(sel), 0) + body)

                self._send_all(shards, payload)
                self._recv_all(shards, None, deadline)

            self._retry_rpc(attempt, op="push_show_click")
        finally:
            for s, _ in shards:
                self._locks[s].release()

    def _simple_cmd_all(self, cmd, table, recv_extra=None):
        """Fire `cmd` at every server; returns the per-server extras."""
        shards = [(i, None) for i in range(len(self.endpoints))]
        outs = [None] * len(self.endpoints)
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            def attempt():
                deadline = self._deadline()
                self._send_all(shards, lambda s, sel: _HDR.pack(
                    cmd, _tname(table), 0, 0))

                def recv_one(s, sel, sk):
                    if recv_extra is not None:
                        outs[s] = recv_extra(sk)

                self._recv_all(shards, recv_one, deadline)

            self._retry_rpc(attempt, op="cmd")
        finally:
            for s, _ in shards:
                self._locks[s].release()
        return outs

    def decay(self, table: str):
        """One show/click time-decay cycle on every server."""
        self._simple_cmd_all(CMD_DECAY, table)

    def shrink(self, table: str) -> int:
        """Evict low-score/expired rows everywhere; total evicted."""
        outs = self._simple_cmd_all(
            CMD_SHRINK, table,
            recv_extra=lambda sk: _LEN.unpack(_recv_exact(sk, 8))[0])
        return int(np.sum([o or 0 for o in outs]))

    # -- table-config negotiation (the reference ships TableAccessor
    #    configs to every server at fleet init; these do it per table) --
    def create_sparse_table(self, table: str, dim: int, optimizer="sgd",
                            lr=0.01, init_std=0.01, seed=0, accessor=None,
                            show_decay_rate=0.98, click_coeff=8.0,
                            delete_threshold=0.8, ttl_days=30.0,
                            beta1=0.9, beta2=0.999, eps=1e-8):
        cfg = _SPARSE_CFG.pack(
            lr, init_std, int(seed), _OPT_IDS[optimizer],
            1 if accessor == "ctr" else 0, beta1, beta2, eps,
            show_decay_rate, click_coeff, delete_threshold, float(ttl_days))
        shards = [(i, None) for i in range(len(self.endpoints))]
        for s, _ in shards:
            self._locks[s].acquire()
        try:
            self._send_all(shards, lambda s, sel: _HDR.pack(
                CMD_ADD_SPARSE, _tname(table), 0, dim) + cfg)
            self._recv_all(shards, None)
        finally:
            for s, _ in shards:
                self._locks[s].release()
        self.register_sparse_dim(table, dim)

    def create_dense_table(self, table: str, total: int, optimizer="sgd",
                           lr=0.01, beta1=0.9, beta2=0.999, eps=1e-8):
        from .table import dense_shard_range
        n_srv = len(self.endpoints)
        for i in range(n_srv):
            lo, hi = dense_shard_range(int(total), i, n_srv)
            cfg = _DENSE_CFG.pack(lr, lo, int(total), _OPT_IDS[optimizer],
                                  beta1, beta2, eps)
            with self._locks[i]:
                sk = self._sock(i)
                sk.sendall(_HDR.pack(CMD_ADD_DENSE, _tname(table), hi - lo, 0)
                           + cfg)
                _check_status(sk)

    # -- graph table (common_graph_table.h role) --
    def sample_neighbors(self, table: str, ids, k: int):
        """[n] node ids -> ([n, k] neighbor ids, [n, k] weights); nodes
        route to their owning server (id % n_servers, like sparse rows)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        shards = self._shard_sel(ids)
        nb = np.full((len(ids), k), -1, np.int64)
        w = np.zeros((len(ids), k), np.float32)
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            self._send_all(shards, lambda s, sel: (
                _HDR.pack(CMD_SAMPLE_NEIGHBORS, _tname(table), len(sel), k)
                + ids[sel].tobytes()))

            def recv_one(s, sel, sk):
                nb[sel] = np.frombuffer(
                    _recv_exact(sk, 8 * len(sel) * k), np.int64
                ).reshape(len(sel), k)
                w[sel] = np.frombuffer(
                    _recv_exact(sk, 4 * len(sel) * k), np.float32
                ).reshape(len(sel), k)

            self._recv_all(shards, recv_one)
        finally:
            for s, _ in shards:
                self._locks[s].release()
        return nb, w

    def node_feat(self, table: str, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        shards = self._shard_sel(ids)
        parts = {}
        for s, sel in shards:
            self._locks[s].acquire()
        try:
            self._send_all(shards, lambda s, sel: (
                _HDR.pack(CMD_NODE_FEAT, _tname(table), len(sel), 0)
                + ids[sel].tobytes()))

            def recv_one(s, sel, sk):
                (d,) = _LEN.unpack(_recv_exact(sk, 8))
                parts[s] = (sel, np.frombuffer(
                    _recv_exact(sk, 4 * len(sel) * d), np.float32
                ).reshape(len(sel), d))

            self._recv_all(shards, recv_one)
        finally:
            for s, _ in shards:
                self._locks[s].release()
        d = max(p.shape[1] for _, p in parts.values())
        out = np.zeros((len(ids), d), np.float32)
        for sel, p in parts.values():
            out[sel, :p.shape[1]] = p
        return out

    def barrier(self, n_trainers: int = 1):
        """Block until `n_trainers` clients reach this point (coordinated by
        server 0 — the gloo-barrier role in the reference's PS bring-up)."""
        with self._locks[0]:
            try:
                sk = self._sock(0)
                sk.sendall(_HDR.pack(CMD_BARRIER, _tname(""), n_trainers, 0))
                # the ACK is legitimately held until all trainers arrive;
                # bound the wait by the server's own barrier timeout
                _check_status(sk, time.monotonic() + _BARRIER_TIMEOUT + 30)
            except OSError:
                self._drop(0)
                raise

    def stop_server(self):
        for s in range(len(self.endpoints)):
            try:
                with self._locks[s]:
                    sk = self._sock(s)
                    sk.sendall(_HDR.pack(CMD_STOP, _tname(""), 0, 0))
                    _check_status(sk)
            except (ConnectionError, OSError, PsError):
                pass

    def close(self):
        for i in range(len(self._chans)):
            self._drop(i)


# ---- single-endpoint HA RPCs (driven by ha.HaPsNode over its own
#      socket; service.py owns the wire structs) ----

def ha_connect(endpoint: str, timeout: Optional[float] = None):
    return _net.dial(endpoint, timeout=timeout or 120, plane="ps")


def rpc_replicate(sock, after_lsn: int, max_records: int = 0,
                  standby_id: str = "", deadline=None):
    """Fetch WAL records with lsn > after_lsn; `after_lsn` is also the
    caller's ack watermark. Returns a list of wal.Record."""
    sock.sendall(_HDR.pack(CMD_REPLICATE, _tname(standby_id), 0, 0)
                 + _LEN.pack(int(after_lsn)) + _LEN.pack(int(max_records)))
    _check_status(sock, deadline)
    (_n,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    (blen,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    return _wal.decode_stream(_recv_exact(sock, blen, deadline))


def rpc_ha_status(sock, deadline=None) -> dict:
    sock.sendall(_HDR.pack(CMD_HA_STATUS, _tname(""), 0, 0))
    _check_status(sock, deadline)
    (ln,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    return json.loads(_recv_exact(sock, ln, deadline).decode())


def rpc_handback(sock, records, deadline=None) -> int:
    blob = b"".join(_wal.encode_record(r) for r in records)
    sock.sendall(_HDR.pack(CMD_HANDBACK, _tname(""), 0, 0)
                 + _LEN.pack(len(blob)) + blob)
    _check_status(sock, deadline)
    (applied,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    return applied


def rpc_fetch_state(sock, deadline=None):
    sock.sendall(_HDR.pack(CMD_FETCH_STATE, _tname(""), 0, 0))
    _check_status(sock, deadline)
    (mlen,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    meta = json.loads(_recv_exact(sock, mlen, deadline).decode())
    (blen,) = _LEN.unpack(_recv_exact(sock, 8, deadline))
    return meta, _recv_exact(sock, blen, deadline)


class Communicator:
    """Async grad sender (communicator.cc role): push_sparse calls are
    queued and flushed by a background thread, overlapping server updates
    with the trainer's next step; `flush()`/`barrier()` give the sync
    points the reference exposes.

    Failover behavior: a TRANSPORT failure no longer poisons the worker —
    the in-flight batch is re-enqueued (bounded by
    `FLAGS_ps_communicator_max_requeues`) and retried with its ORIGINAL
    per-server seqs, so whatever the dying primary already applied and
    replicated is dropped by the survivor's ledger, not double-applied.
    Server-reported PsErrors (application failures) still fail the
    worker permanently."""

    def __init__(self, client: PsClient, max_queue=64):
        self.client = client
        import collections
        import queue as q
        self._q = q.Queue(maxsize=max_queue)
        # pending counts enqueued-but-not-yet-applied items; a Condition
        # (not q.empty + idle flag) closes the pop-before-clear race where
        # flush() could return while the last push was still in flight
        self._pending = 0
        self._cond = threading.Condition()
        self._error: Optional[BaseException] = None
        self._max_requeues = int(_flags.flag("ps_communicator_max_requeues"))
        # requeued batches live in a worker-local deque, NOT back in the
        # bounded queue: the worker blocking on its own full queue would
        # deadlock against the producers it is supposed to drain
        self._retry = collections.deque()
        self._thread = _syncwatch.Thread(target=self._run, daemon=True,
                                        name="ps-communicator")
        self._thread.start()

    def _run(self):
        while True:
            item = self._retry.popleft() if self._retry else self._q.get()
            if item is None:
                return
            kind, table, a, b, seqs, tries = item
            try:
                if self._error is None:
                    if kind == "sparse":
                        self.client.push_sparse(table, a, b, _seqs=seqs)
                    else:
                        self.client.push_dense(table, a, _seqs=seqs)
            except PsError as e:  # application failure: permanent
                self._error = e
            except BaseException as e:
                if tries < self._max_requeues:
                    # count the requeue into pending BEFORE the finally
                    # block decrements this attempt, so a concurrent
                    # flush() can never observe a false zero
                    with self._cond:
                        self._pending += 1
                    self._retry.append((kind, table, a, b, seqs, tries + 1))
                    if _monitor._ENABLED:
                        _monitor.count("ps.communicator.requeues")
                    time.sleep(self.client.backoff_s)
                else:
                    self._error = e
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending == 0:
                        self._cond.notify_all()

    def _raise_if_failed(self):
        if self._error is not None:
            raise RuntimeError(
                "Communicator push failed; queued gradients were dropped"
            ) from self._error

    def _put(self, item):
        self._raise_if_failed()
        with self._cond:
            self._pending += 1
        self._q.put(item)

    def push_sparse_async(self, table, ids, grads):
        self._put(("sparse", table, np.asarray(ids), np.asarray(grads),
                   {}, 0))

    def push_dense_async(self, table, grad):
        self._put(("dense", table, np.asarray(grad), None, {}, 0))

    def pending(self) -> int:
        """Batches enqueued or in flight but not yet applied."""
        with self._cond:
            return self._pending

    def flush(self, timeout=30.0, on_timeout="requeue"):
        """Block until every queued push applied (or permanently failed).

        On timeout the behavior is DETERMINISTIC, never
        silently-dropped work (`ps.communicator.flush_timeouts` counts
        either way):

        - ``on_timeout="requeue"`` (default): raise
          `CommunicatorFlushTimeout` carrying the pending batch count.
          Every undelivered batch stays parked in the worker with its
          ORIGINAL per-server seqs — the ledger keeps the retries
          exactly-once — so a later flush()/stop() delivers exactly
          what this one could not.
        - ``on_timeout="drain"``: keep waiting past the deadline until
          the queue drains or a permanent error is recorded. The
          elapsed timeout is reported via the counter only.
        """
        if on_timeout not in ("requeue", "drain"):
            raise ValueError(f"flush: unknown on_timeout={on_timeout!r}")
        with self._cond:
            if not self._cond.wait_for(lambda: self._pending == 0,
                                       timeout=timeout):
                if _monitor._ENABLED:
                    _monitor.count("ps.communicator.flush_timeouts")
                if on_timeout == "requeue":
                    raise CommunicatorFlushTimeout(
                        f"Communicator flush timed out after {timeout}s "
                        f"with {self._pending} batch(es) pending; they "
                        "remain queued with their original seqs",
                        pending=self._pending)
                # drain: a permanent error also releases the wait —
                # the worker stops applying and pending hits zero as
                # remaining items fall through the error check
                self._cond.wait_for(lambda: self._pending == 0)
        self._raise_if_failed()

    def stop(self):
        """Drain and shut down the worker; the thread is always joined and
        any recorded push error re-raised AFTER cleanup."""
        err: Optional[BaseException] = None
        try:
            self.flush()
        except BaseException as e:
            err = e
        self._q.put(None)
        self._thread.join(timeout=5)
        if err is not None:
            raise err
