"""PS RPC plane: threaded socket server + sharded client + async Communicator.

Reference parity: `ps/service/brpc_ps_client.h` / `brpc_ps_server.cc`
(pull/push dense+sparse RPCs), `ps/service/communicator/communicator.cc:1`
(async grad send batching), proto `sendrecv.proto`.

Redesign: brpc is replaced by a length-prefixed binary protocol over raw
sockets (the C++ TCPStore's wire style) — header `cmd table n_ids dim` +
raw little-endian buffers, no pickle on the hot path. Sparse tables shard
across servers by `id % n_servers`; dense tables live on server 0.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .table import DenseTable, SparseTable

_HDR = struct.Struct("<B16sqq")  # cmd, table name (padded), n, dim
CMD_PULL_SPARSE = 1
CMD_PUSH_SPARSE = 2
CMD_PULL_DENSE = 3
CMD_PUSH_DENSE = 4
CMD_STOP = 5
CMD_BARRIER = 6
_OK = b"\x01"


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("ps: peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _tname(name: str) -> bytes:
    return name.encode()[:16].ljust(16, b"\0")


class PsServer:
    """One parameter-server process/thread (brpc_ps_server role)."""

    def __init__(self, host="127.0.0.1", port=0):
        self._tables: Dict[str, object] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._barrier_count = 0
        self._barrier_lock = threading.Lock()

    def add_sparse_table(self, name, dim, **kw):
        self._tables[name] = SparseTable(dim, **kw)
        return self._tables[name]

    def add_dense_table(self, name, shape, **kw):
        self._tables[name] = DenseTable(shape, **kw)
        return self._tables[name]

    def table(self, name):
        return self._tables[name]

    def run(self, block=False):
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if block:
            self._thread.join()
        return self

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                cmd, name, n, dim = _HDR.unpack(hdr)
                name = name.rstrip(b"\0").decode()
                if cmd == CMD_STOP:
                    conn.sendall(_OK)
                    self._stop.set()
                    return
                if cmd == CMD_BARRIER:
                    with self._barrier_lock:
                        self._barrier_count += 1
                    conn.sendall(_OK)
                    continue
                tbl = self._tables[name]
                if cmd == CMD_PULL_SPARSE:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    rows = tbl.pull(ids)
                    conn.sendall(rows.astype(np.float32).tobytes())
                elif cmd == CMD_PUSH_SPARSE:
                    ids = np.frombuffer(_recv_exact(conn, 8 * n), np.int64)
                    grads = np.frombuffer(
                        _recv_exact(conn, 4 * n * dim), np.float32
                    ).reshape(n, dim)
                    tbl.push(ids, grads)
                    conn.sendall(_OK)
                elif cmd == CMD_PULL_DENSE:
                    w = tbl.pull().astype(np.float32)
                    conn.sendall(struct.pack("<q", w.size) + w.tobytes())
                elif cmd == CMD_PUSH_DENSE:
                    g = np.frombuffer(_recv_exact(conn, 4 * n), np.float32)
                    tbl.push(g.reshape(tbl.w.shape))
                    conn.sendall(_OK)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2)


class PsClient:
    """Sharded client (brpc_ps_client role): sparse ids route to server
    `id % n_servers`; dense tables live on server 0."""

    def __init__(self, endpoints: Sequence[str]):
        self.endpoints = list(endpoints)
        self._socks: List[Optional[socket.socket]] = [None] * len(endpoints)
        self._locks = [threading.Lock() for _ in endpoints]
        self._dims: Dict[str, int] = {}  # table -> row dim (accessor config)

    def _sock(self, i):
        if self._socks[i] is None:
            host, port = self.endpoints[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=30)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[i] = s
        return self._socks[i]

    # -- sparse --
    def pull_sparse(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        dim = self._dims[table]
        n_srv = len(self.endpoints)
        out = np.empty((len(ids), dim), np.float32)
        for s in range(n_srv):
            sel = np.where(ids % n_srv == s)[0]
            if len(sel) == 0:
                continue
            sub = ids[sel]
            with self._locks[s]:
                sk = self._sock(s)
                sk.sendall(_HDR.pack(CMD_PULL_SPARSE, _tname(table),
                                     len(sub), 0) + sub.tobytes())
                rows = np.frombuffer(
                    _recv_exact(sk, 4 * len(sub) * dim), np.float32
                ).reshape(len(sub), dim)
            out[sel] = rows
        return out

    def register_sparse_dim(self, table: str, dim: int):
        """Client-side table metadata (the reference ships this in the
        TableAccessor config)."""
        self._dims[table] = dim

    def push_sparse(self, table: str, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        n_srv = len(self.endpoints)
        for s in range(n_srv):
            sel = np.where(ids % n_srv == s)[0]
            if len(sel) == 0:
                continue
            sub, g = ids[sel], grads[sel]
            with self._locks[s]:
                sk = self._sock(s)
                sk.sendall(_HDR.pack(CMD_PUSH_SPARSE, _tname(table),
                                     len(sub), g.shape[1])
                           + sub.tobytes() + g.tobytes())
                _recv_exact(sk, 1)

    # -- dense --
    def pull_dense(self, table: str) -> np.ndarray:
        with self._locks[0]:
            sk = self._sock(0)
            sk.sendall(_HDR.pack(CMD_PULL_DENSE, _tname(table), 0, 0))
            (size,) = struct.unpack("<q", _recv_exact(sk, 8))
            return np.frombuffer(_recv_exact(sk, 4 * size), np.float32).copy()

    def push_dense(self, table: str, grad):
        g = np.asarray(grad, np.float32).reshape(-1)
        with self._locks[0]:
            sk = self._sock(0)
            sk.sendall(_HDR.pack(CMD_PUSH_DENSE, _tname(table), g.size, 0)
                       + g.tobytes())
            _recv_exact(sk, 1)

    def barrier(self):
        for s in range(len(self.endpoints)):
            with self._locks[s]:
                sk = self._sock(s)
                sk.sendall(_HDR.pack(CMD_BARRIER, _tname(""), 0, 0))
                _recv_exact(sk, 1)

    def stop_server(self):
        for s in range(len(self.endpoints)):
            try:
                with self._locks[s]:
                    sk = self._sock(s)
                    sk.sendall(_HDR.pack(CMD_STOP, _tname(""), 0, 0))
                    _recv_exact(sk, 1)
            except (ConnectionError, OSError):
                pass

    def close(self):
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass


class Communicator:
    """Async grad sender (communicator.cc role): push_sparse calls are
    queued and flushed by a background thread, overlapping server updates
    with the trainer's next step; `flush()`/`barrier()` give the sync
    points the reference exposes."""

    def __init__(self, client: PsClient, max_queue=64):
        self.client = client
        import queue as q
        self._q = q.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            self._idle.clear()
            kind, table, a, b = item
            try:
                if kind == "sparse":
                    self.client.push_sparse(table, a, b)
                else:
                    self.client.push_dense(table, a)
            finally:
                if self._q.empty():
                    self._idle.set()

    def push_sparse_async(self, table, ids, grads):
        self._q.put(("sparse", table, np.asarray(ids), np.asarray(grads)))

    def push_dense_async(self, table, grad):
        self._q.put(("dense", table, np.asarray(grad), None))

    def flush(self, timeout=30.0):
        t0 = time.time()
        while not (self._q.empty() and self._idle.is_set()):
            if time.time() - t0 > timeout:
                raise TimeoutError("Communicator flush timed out")
            time.sleep(0.005)

    def stop(self):
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=5)
